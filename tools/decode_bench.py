#!/usr/bin/env python
"""Decode (generation) throughput: KV-cache vs full-recompute, on-chip.

The training side has tokens/sec + MFU north stars (BASELINE.md); this is
the inference twin — tokens/sec and per-token latency for
tpu_dist.engine.generate at an LM-bench-class geometry. The KV-cache path
embeds ONE token per tick and attends over the cache (O(L*d) per token);
the full-recompute path re-runs the whole prefix every tick (O(L^2*d)) —
this tool puts the factor between them on record.

``--requests N`` additionally runs N sequential warm KV-cache calls as
individual *requests* and reports per-request latency percentiles
(p50/p99) plus request tok/s in the headline JSON — the first
scrape-able serving SLO. With ``--ledger`` (or ``BENCH_LEDGER``) each
request lands as one ``decode`` ledger event, so
``tools/ledger_report.py`` renders the same percentiles in its decode
section.

``--trace N`` switches to REQUEST-TRACE REPLAY through the
continuous-batching engine (engine.serve + the paged KV cache): N
requests with seeded Poisson arrivals and mixed prompt/output lengths
stream through the scheduler, and the SAME trace then replays through
static batching (drain refill) at equal slot capacity. The headline JSON
gains a ``serving`` block — completed requests/s (wall AND per-tick, the
deterministic twin), TTFT and per-output-token latency p50/p99, batch
occupancy, and the static baseline — making throughput-UNDER-LOAD the
recorded metric; ``tools/bench_track.py`` gates on it like ``data_s``.
Arrivals are scheduled in TICK units from a seeded rng, so the schedule
(and the per-tick numbers) are machine-speed-independent. ``--spec-k``
runs the trace through the speculative tick (``accepted_per_tick`` joins
the block), and ``--prefix-tenants``/``--prefix-len`` give requests
shared per-tenant system prompts with CoW prefix caching on — plus a
cache-off baseline replay, so the ``pages_per_request`` drop is on
record (``prefix_hit_rate`` says why).

Usage:
    python tools/decode_bench.py                         # both paths
    python tools/decode_bench.py --steps 512 --batch 16
    python tools/decode_bench.py --requests 16 --ledger dec.jsonl
    python tools/decode_bench.py --trace 64 --serve-slots 8
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pctl_ms(xs, q):
    """Nearest-rank percentile of a list of seconds, in ms — THE repo
    percentile (tools/ledger_report._pctl), ms-scaled, so the bench and
    the report can never disagree on rank convention."""
    from tools.ledger_report import _pctl

    v = _pctl(sorted(xs), q)
    return None if v is None else round(v * 1e3, 3)


def _drive_trace(eng, arrivals, prompts, outs):
    """Replay one arrival schedule through a ServeEngine: requests are
    submitted when the WALL tick (loop iteration) reaches their arrival
    tick — idle iterations cost nothing, so the schedule stays
    deterministic whatever the machine speed. Returns (completions,
    elapsed_wall_s)."""
    import time as _t

    from tpu_dist.engine.serve import DecodeRequest

    n = len(prompts)
    i = 0
    wall_tick = 0
    comps = []
    t0 = _t.perf_counter()
    while i < n or eng.queue or any(s is not None for s in eng.slots):
        while i < n and arrivals[i] <= wall_tick:
            eng.submit(DecodeRequest(i, prompts[i], int(outs[i])))
            i += 1
        comps.extend(eng.step())
        wall_tick += 1
        if wall_tick > 1_000_000:
            raise RuntimeError("trace replay did not drain")
    return comps, _t.perf_counter() - t0


class _VirtualClock:
    """Deterministic engine clock for the long-context replay: one unit
    is one TOKEN-EQUIVALENT of scheduler-step cost. Each iteration costs
    ``tick_floor`` (the decode dispatch everyone pays) plus however many
    prefill tokens that iteration actually pushed (the engine's
    ``prefill_token_work`` delta) — so a monolithic 16k admit shows up as
    one enormous inter-token gap for every concurrently-decoding request,
    while chunked prefill amortizes the same work into
    ``prefill_chunk``-sized bumps. The TPOT-interference number is then
    pure cost-model arithmetic: machine-independent, warm-up-free, and
    assertable in CI (the wall-clock twin would be noise on shared
    runners)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _drive_longcontext(eng, clock, reqs, floor):
    """Replay a long-context trace under the virtual cost-model clock:
    arrivals are in scheduler-ITERATION units, and the clock advances by
    floor + this iteration's prefill-token work after every step."""
    from tpu_dist.engine.serve import DecodeRequest

    n = len(reqs)
    i = 0
    it = 0
    comps = []
    while i < n or eng.queue or any(s is not None for s in eng.slots):
        while i < n and reqs[i]["arrival"] <= it:
            eng.submit(DecodeRequest(i, reqs[i]["prompt"],
                                     int(reqs[i]["out_len"])))
            i += 1
        work0 = eng.prefill_token_work
        comps.extend(eng.step())
        clock.t += floor + (eng.prefill_token_work - work0)
        it += 1
        if it > 1_000_000:
            raise RuntimeError("long-context replay did not drain")
    return comps


def replay_long_context(args, model, params, trace=None):
    """--long-context / --prompt-len-dist: the mixed-traffic tail-latency
    benchmark. A trace whose prompt lengths span orders of magnitude
    (tools/traces/longcontext_mix.json ships a 16k admit among short
    interactive requests) replays through chunked prefill under the
    virtual cost-model clock, and the SAME trace with the long prompts
    REMOVED replays as the interference baseline. The headline gains:

    * ``ttft_long_p99``   — TTFT p99 of the long (>= long_threshold)
      requests, in virtual token-equivalents: the price of admitting a
      book-length prompt at all;
    * ``tpot_interference_pct`` — how much the SHORT requests' TPOT p99
      degrades when the long prompts are in flight, vs the no-long
      baseline. Chunked prefill's whole claim is that this stays bounded
      by chunk/tick_floor instead of exploding by prompt_len/tick_floor
      (``--long-monolithic`` puts the unchunked contrast on record);
    * ``sp_capacity``     — with ``--sp-capacity N``: a context longer
      than ONE device's page budget served end-to-end on an N-device CPU
      sp submesh (the sharded-pool existence proof, geometry-tiny).

    ``tools/bench_track.py`` gates the first two like ``data_s``
    (abstaining on pre-long-context history)."""
    import numpy as np

    from tpu_dist.engine.serve import (DecodeRequest, ServeConfig,
                                       ServeEngine)

    if trace is None and args.long_context:
        with open(args.long_context) as f:
            trace = json.load(f)
    if trace is None:
        # --prompt-len-dist "LEN:WEIGHT,LEN:WEIGHT,...": draw the trace's
        # prompt lengths from the weighted mixture, everything else from
        # the standard seeded Poisson machinery
        pairs = [p.split(":") for p in args.prompt_len_dist.split(",")]
        lens = np.array([int(l) for l, _ in pairs])
        weights = np.array([float(w) for _, w in pairs], dtype=float)
        weights = weights / weights.sum()
        count = args.trace or 32
        rng = np.random.default_rng(args.trace_seed)
        gaps = rng.exponential(1.0 / max(args.arrival_rate, 1e-9), count)
        arrivals = np.floor(np.cumsum(gaps)).astype(int)
        plens = rng.choice(lens, size=count, p=weights)
        outs = rng.integers(args.min_out, args.max_out + 1, count)
        trace = {"seed": args.trace_seed, "tick_floor": args.tick_floor,
                 "long_threshold": args.long_threshold,
                 "requests": [
                     {"arrival": int(a), "prompt_len": int(p),
                      "out_len": int(o)}
                     for a, p, o in zip(arrivals, plens, outs)]}
    floor = trace["tick_floor"]
    thr = trace["long_threshold"]
    rng = np.random.default_rng(trace["seed"])
    reqs = [dict(r) for r in trace["requests"]]
    for r in reqs:
        # token content drawn in trace order from the trace seed: the
        # replay is bit-reproducible from the JSON alone
        r["prompt"] = rng.integers(0, args.vocab_size,
                                   (r["prompt_len"],)).astype(np.int32)
    max_total = max(r["prompt_len"] + r["out_len"] for r in reqs)
    pages_per_seq = -(-max_total // args.page_size)
    num_pages = args.num_pages or args.serve_slots * pages_per_seq

    def run(subset, chunk):
        clock = _VirtualClock()
        eng = ServeEngine(model, params, ServeConfig(
            max_slots=args.serve_slots, page_size=args.page_size,
            num_pages=num_pages, max_len=max_total,
            quant=args.serve_quant, kv_quant=args.kv_quant,
            prefill_chunk=chunk), now_fn=clock)
        comps = _drive_longcontext(eng, clock, subset, floor)
        return comps, eng

    def _p99(xs):
        from tools.ledger_report import _pctl

        v = _pctl(sorted(xs), 99)
        return None if v is None else round(v, 3)

    def short_tpots(comps, subset):
        return [(c.finish_ts - c.first_token_ts) / (c.n_generated - 1)
                for c in comps if c.n_generated > 1
                and subset[c.rid]["prompt_len"] < thr]

    chunk = args.prefill_chunk
    comps, eng = run(reqs, chunk)
    ttft_long = [c.ttft_s for c in comps
                 if reqs[c.rid]["prompt_len"] >= thr]
    tpot_mixed = _p99(short_tpots(comps, reqs))
    shorts_only = [r for r in reqs if r["prompt_len"] < thr]
    base_comps, _ = run(shorts_only, chunk)
    tpot_base = _p99(short_tpots(base_comps, shorts_only))
    interference = (None if not tpot_base or tpot_mixed is None
                    else round((tpot_mixed - tpot_base) / tpot_base * 100,
                               2))
    serving = {
        "mode": "long_context",
        "requests": len(reqs),
        "long_requests": len(reqs) - len(shorts_only),
        "completed": len(comps),
        "ticks": eng.ticks, "chunk_ticks": eng.chunk_ticks,
        "requests_per_tick": round(len(comps) / max(eng.ticks, 1), 4),
        "prefill_token_work": eng.prefill_token_work,
        "prefill_chunk": chunk, "tick_floor": floor,
        "long_threshold": thr,
        "trace_seed": trace["seed"],
        "slots": args.serve_slots, "page_size": args.page_size,
        "num_pages": num_pages, "kv_quant": args.kv_quant,
        "occupancy": round(eng.occupancy, 4),
        # virtual token-equivalent units throughout (see _VirtualClock)
        "ttft_long_p99": _p99(ttft_long),
        "tpot_short_p99": tpot_mixed,
        "tpot_baseline_p99": tpot_base,
        "tpot_interference_pct": interference,
    }
    print(f"serve[long-context]: {len(comps)}/{len(reqs)} completed "
          f"({serving['long_requests']} long >= {thr} tok) in {eng.ticks} "
          f"ticks + {eng.chunk_ticks} chunk ticks; TTFT-long p99 "
          f"{serving['ttft_long_p99']}, short-TPOT interference "
          f"{interference}% (chunk {chunk}, floor {floor})",
          file=sys.stderr)
    if getattr(args, "long_monolithic", False):
        # the unchunked contrast: same trace, prefill_chunk=0 — the
        # full-prompt stall lands in every concurrent short's TPOT
        mono_comps, mono_eng = run(reqs, 0)
        mono_p99 = _p99(short_tpots(mono_comps, reqs))
        serving["monolithic"] = {
            "tpot_short_p99": mono_p99,
            "tpot_interference_pct": (
                None if not tpot_base or mono_p99 is None
                else round((mono_p99 - tpot_base) / tpot_base * 100, 2)),
            "ticks": mono_eng.ticks,
        }
        print(f"serve[long-context]: monolithic contrast interference "
              f"{serving['monolithic']['tpot_interference_pct']}%",
              file=sys.stderr)
    serving["sp_capacity"] = None
    if args.sp_capacity > 0:
        import jax

        from tpu_dist.parallel.mesh import SP_AXIS, make_mesh

        n = args.sp_capacity
        if len(jax.devices()) < n:
            print(f"serve[long-context]: sp capacity proof skipped "
                  f"({len(jax.devices())} devices < {n}; set XLA_FLAGS="
                  f"--xla_force_host_platform_device_count={n})",
                  file=sys.stderr)
        else:
            # geometry-tiny existence proof: per-device budget 2 pages of
            # 4 tokens, context > that budget, bit-served on the submesh
            ps = 4
            mesh = make_mesh((n,), (SP_AXIS,),
                             devices=jax.devices()[:n])
            eng_sp = ServeEngine(model, params, ServeConfig(
                max_slots=1, page_size=ps, num_pages=2 * n,
                max_len=8 * n, quant=args.serve_quant,
                sp_prefill_threshold=ps + 1), mesh=mesh)
            plen, out_len = 5 * n + 1, n + 2
            sp_prompt = np.random.default_rng(trace["seed"]).integers(
                0, args.vocab_size, (plen,)).astype(np.int32)
            sp_comps = eng_sp.run([DecodeRequest(0, sp_prompt, out_len)])
            budget = eng_sp.pool.pages_per_device * ps
            serving["sp_capacity"] = {
                "devices": n, "page_size": ps,
                "pages_per_device": eng_sp.pool.pages_per_device,
                "device_token_budget": budget,
                "context_tokens": plen + out_len,
                "exceeds_single_device": plen + out_len > budget,
                "completed": len(sp_comps),
                "sp_prefills": eng_sp.sp_prefills,
            }
            print(f"serve[long-context]: sp capacity — "
                  f"{plen + out_len}-token context on {n} devices of "
                  f"{budget}-token budget each "
                  f"({len(sp_comps)} completed)", file=sys.stderr)
    return serving


def replay_serving_trace(args, model, params, ledger=None):
    """--trace: the throughput-under-load benchmark. One seeded trace
    (Poisson arrivals in tick units, mixed prompt/output lengths) replays
    through continuous batching AND through static drain-batching at equal
    slot capacity; the returned dict is the headline's ``serving`` block.
    A warm pass (full replay, discarded) pays the prefill-bucket and tick
    compiles so both timed modes run warm.

    ``--prefix-tenants T`` prepends one of T fixed per-tenant system
    prompts (``--prefix-len`` tokens, seeded) to every request — the
    shared-prefix traffic shape real multi-tenant serving has — and
    enables copy-on-write prefix caching; a third replay with the cache
    OFF becomes the ``no_prefix_cache`` baseline, so the
    ``pages_per_request`` drop is measured, not asserted. ``--spec-k``
    runs the speculative tick (self-speculation: the base drafts for
    itself) and publishes ``accepted_per_tick``. Both knobs only shape
    the seeded schedule deterministically — per-tick numbers stay
    machine-independent."""
    import numpy as np

    from tools.request_report import (requests_summary, slowest_traces,
                                      waterfall_lines)
    from tpu_dist.engine.serve import ServeConfig, ServeEngine
    from tpu_dist.obs import reqtrace
    from tpu_dist.obs.ledger import Ledger

    rng = np.random.default_rng(args.trace_seed)
    gaps = rng.exponential(1.0 / max(args.arrival_rate, 1e-9), args.trace)
    arrivals = np.floor(np.cumsum(gaps)).astype(int)
    prompts = [rng.integers(0, args.vocab_size,
                            (int(rng.integers(args.min_prompt,
                                              args.max_prompt + 1)),)
                            ).astype(np.int32)
               for _ in range(args.trace)]
    outs = rng.integers(args.min_out, args.max_out + 1, args.trace)
    prefix_on = args.prefix_tenants > 0
    prefix_len = args.prefix_len if prefix_on else 0
    if prefix_on:
        # per-tenant system prompts, drawn AFTER the base trace so the
        # pre-existing schedule (and its tracked numbers) is unchanged
        # when the knob is off
        tenants = [rng.integers(0, args.vocab_size,
                                (args.prefix_len,)).astype(np.int32)
                   for _ in range(args.prefix_tenants)]
        tenant_of = rng.integers(0, args.prefix_tenants, args.trace)
        prompts = [np.concatenate([tenants[tenant_of[j]], prompts[j]])
                   for j in range(args.trace)]
    max_total = prefix_len + args.max_prompt + args.max_out
    pages_per_seq = -(-max_total // args.page_size)
    num_pages = args.num_pages or args.serve_slots * pages_per_seq

    def make(refill, led=None, prefix_cache=prefix_on):
        return ServeEngine(model, params, ServeConfig(
            max_slots=args.serve_slots, page_size=args.page_size,
            num_pages=num_pages, max_len=max_total,
            quant=args.serve_quant, kv_quant=args.kv_quant,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, refill=refill, spec_k=args.spec_k,
            prefix_cache=prefix_cache,
            kv_event_every=32), ledger=led)

    _drive_trace(make("continuous"), arrivals, prompts, outs)  # warm
    # the continuous (headline) mode always runs with a span-capturing
    # ledger: the engine's per-request spans (obs.reqtrace) feed the
    # tail_attribution block and --waterfalls without needing --ledger
    span_cap = []
    cont_led = ledger if ledger is not None else Ledger(None)
    cont_led.add_sink(span_cap.append)
    results = {}
    modes = [("continuous", True), ("drain", True)]
    if prefix_on:
        # the CoW baseline: same trace, same scheduler, cache off — the
        # pages_per_request delta is the prefix cache's whole claim
        modes.append(("no_prefix_cache", False))
    for refill, prefix_cache in modes:
        eng = make("continuous" if refill == "no_prefix_cache" else refill,
                   led=cont_led if refill == "continuous" else None,
                   prefix_cache=prefix_cache)
        comps, elapsed = _drive_trace(eng, arrivals, prompts, outs)
        ttft = [c.ttft_s for c in comps]
        tpot = [(c.finish_ts - c.first_token_ts) / (c.n_generated - 1)
                for c in comps if c.n_generated > 1]
        waits = [c.queue_wait_s for c in comps]
        toks = sum(c.n_generated for c in comps)
        apt = eng.accepted_per_tick
        results[refill] = {
            "completed": len(comps), "rejected": eng.rejected,
            "ticks": eng.ticks,
            "requests_per_tick": (round(len(comps) / eng.ticks, 4)
                                  if eng.ticks else None),
            "requests_per_sec": (round(len(comps) / elapsed, 2)
                                 if elapsed else None),
            "tokens_per_sec": (round(toks / elapsed, 1)
                               if elapsed else None),
            "occupancy": round(eng.occupancy, 4),
            # per-active-slot tokens per tick: identically 1.0 for the
            # plain tick, > 1.0 once speculative acceptance lands
            "accepted_per_tick": (round(apt, 4) if apt is not None
                                  else (1.0 if eng.ticks else None)),
            # fresh pages granted per completed request — the number the
            # prefix cache exists to shrink
            "pages_per_request": (round(eng.pool.alloc_total / len(comps),
                                        4) if comps else None),
            "prefix_hit_rate": (round(eng.prefix_hit_rate, 4)
                                if eng.prefix_hit_rate is not None
                                else None),
            "cow_copies": eng.pool.cow_copies,
            "ttft_ms": {"p50": _pctl_ms(ttft, 50),
                        "p99": _pctl_ms(ttft, 99)},
            "tpot_ms": {"p50": _pctl_ms(tpot, 50),
                        "p99": _pctl_ms(tpot, 99)},
            "queue_wait_ms": {"p50": _pctl_ms(waits, 50),
                              "p99": _pctl_ms(waits, 99)},
        }
        print(f"serve[{refill}]: {len(comps)}/{args.trace} completed in "
              f"{eng.ticks} ticks ({results[refill]['requests_per_tick']} "
              f"req/tick, {results[refill]['requests_per_sec']} req/s, "
              f"{results[refill]['accepted_per_tick']} accepted/tick, "
              f"{results[refill]['pages_per_request']} pages/req), "
              f"occupancy {eng.occupancy * 100:.0f}%, TTFT p50 "
              f"{results[refill]['ttft_ms']['p50']}ms", file=sys.stderr)
    serving = dict(results["continuous"])
    serving["requests"] = args.trace
    serving["slots"] = args.serve_slots
    serving["page_size"] = args.page_size
    serving["num_pages"] = num_pages
    serving["kv_quant"] = args.kv_quant
    serving["arrival_rate"] = args.arrival_rate
    serving["trace_seed"] = args.trace_seed
    serving["spec_k"] = args.spec_k
    serving["prefix_tenants"] = args.prefix_tenants
    serving["prefix_len"] = prefix_len
    serving["static"] = results["drain"]
    if prefix_on:
        serving["no_prefix_cache"] = results["no_prefix_cache"]
    # the request-observatory view of the continuous replay: the captured
    # span stream is the same record shape tools/request_report.py reads
    # off a ledger, so the headline carries per-request attribution
    # (bench_track gates coverage) and --waterfalls renders the slowest
    # requests' span trees
    summary = requests_summary(span_cap)
    ta = summary.get("tail_attribution")
    serving["tail_attribution"] = ta
    if ta:
        print(f"serve[traces]: {summary['completed_requests']} request "
              f"trace(s), coverage {ta['coverage']}, sum-check "
              f"{'OK' if ta['sum_check']['ok'] else 'FAILED'} "
              f"(max residue {ta['sum_check']['max_residue_s']:.6g}s)",
              file=sys.stderr)
    n_falls = getattr(args, "waterfalls", 0)
    if n_falls > 0:
        traces = reqtrace.traces(span_cap)
        slow = slowest_traces(traces, n_falls)
        print(f"serve[traces]: {len(slow)} slowest request waterfall(s):",
              file=sys.stderr)
        for tr in slow:
            for line in waterfall_lines(tr):
                print("  " + line, file=sys.stderr)
    return serving


def main():
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--steps", type=int, default=384)
    ap.add_argument("--vocab-size", type=int, default=32000)
    ap.add_argument("--d-model", type=int, default=1024)
    ap.add_argument("--num-layers", type=int, default=8)
    ap.add_argument("--num-heads", type=int, default=8)
    ap.add_argument("--precision", default="bf16", choices=["fp32", "bf16"])
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--top-p", type=float, default=0.0)
    ap.add_argument("--tp", type=int, default=0,
                    help="decode over a ('model',) mesh of this many devices "
                         "(Megatron head/vocab sharding + heads-sharded KV "
                         "cache; engine.generate mesh path). 0 = no mesh. "
                         "The decode tick is weight-bandwidth-bound, so TP "
                         "cuts ms/token ~linearly when devices exist.")
    ap.add_argument("--dp", type=int, default=0,
                    help="decode over a ('data',) mesh: batch-sharded")
    ap.add_argument("--num-experts", type=int, default=0,
                    help="bench the MoE LM (cached decode via the shared "
                         "attend_maybe_cached) instead of the dense one")
    ap.add_argument("--capacity-factor", type=float, default=1.25)
    ap.add_argument("--skip-full", action="store_true",
                    help="skip the O(L^2) full-recompute reference "
                         "(slow at long totals)")
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--requests", type=int, default=8,
                    help="sequential warm kv-cache calls timed as "
                         "individual requests for the latency percentiles "
                         "(0 = skip the per-request section)")
    ap.add_argument("--ledger", default=os.environ.get("BENCH_LEDGER", ""),
                    help="JSONL run ledger: one 'decode' event per request "
                         "(tools/ledger_report.py renders p50/p99 from it)")
    ap.add_argument("--trace", type=int, default=0,
                    help="request-trace replay through the continuous-"
                         "batching engine (engine.serve): this many "
                         "requests with seeded Poisson arrivals and mixed "
                         "lengths, plus a static-batching baseline at "
                         "equal capacity; adds the 'serving' block to the "
                         "headline JSON (0 = off)")
    ap.add_argument("--trace-seed", type=int, default=0)
    ap.add_argument("--waterfalls", type=int, default=0,
                    help="after the trace replay, print this many slowest "
                         "request waterfalls (span trees from "
                         "obs.reqtrace) to stderr (0 = off)")
    ap.add_argument("--arrival-rate", type=float, default=1.0,
                    help="mean request arrivals per decode tick (Poisson)")
    ap.add_argument("--min-prompt", type=int, default=4)
    ap.add_argument("--max-prompt", type=int, default=32)
    ap.add_argument("--min-out", type=int, default=4)
    ap.add_argument("--max-out", type=int, default=64)
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative decoding for the trace replay: this "
                         "many greedy draft tokens per tick "
                         "(self-speculation; 0 = plain decode). Greedy "
                         "output is token-identical either way — only "
                         "accepted_per_tick moves")
    ap.add_argument("--prefix-tenants", type=int, default=0,
                    help="shared-prefix traffic for the trace replay: "
                         "each request gets one of this many fixed "
                         "per-tenant system prompts prepended, and "
                         "copy-on-write prefix caching turns on (plus a "
                         "cache-off baseline replay). 0 = off")
    ap.add_argument("--prefix-len", type=int, default=32,
                    help="tokens per tenant system prompt "
                         "(with --prefix-tenants)")
    ap.add_argument("--serve-slots", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=0,
                    help="paged KV pool size (0 = auto: slots x pages for "
                         "the worst-case sequence)")
    ap.add_argument("--kv-quant", default="none", choices=["none", "int8"],
                    help="page arenas int8+scales (the PR 9 quantize_kv "
                         "layout) instead of the model dtype")
    ap.add_argument("--serve-quant", default="none",
                    choices=["none", "int8", "int8_wo"],
                    help="weight quant for the serving engine "
                         "(engine.generate._quantize_for_decode)")
    ap.add_argument("--long-context", default="",
                    help="path to a long-context trace JSON (e.g. "
                         "tools/traces/longcontext_mix.json): mixed "
                         "short/long traffic replayed through chunked "
                         "prefill under the virtual cost-model clock; "
                         "adds serving.ttft_long_p99 and "
                         "serving.tpot_interference_pct to the headline "
                         "(replaces the one-shot decode sections)")
    ap.add_argument("--prompt-len-dist", default="",
                    help="generate the long-context trace instead of "
                         "loading one: 'LEN:WEIGHT,LEN:WEIGHT,...' "
                         "weighted prompt-length mixture (--trace N "
                         "requests, --trace-seed, --arrival-rate, "
                         "--min-out/--max-out as usual)")
    ap.add_argument("--prefill-chunk", type=int, default=128,
                    help="chunk size for the long-context replay "
                         "(ServeConfig.prefill_chunk; 0 = monolithic)")
    ap.add_argument("--long-threshold", type=int, default=1024,
                    help="prompts at least this long count as 'long' for "
                         "ttft_long_p99 / the interference baseline "
                         "(--prompt-len-dist mode; trace files carry "
                         "their own)")
    ap.add_argument("--tick-floor", type=int, default=1024,
                    help="virtual cost of one scheduler step before "
                         "prefill work, in token-equivalents "
                         "(--prompt-len-dist mode; trace files carry "
                         "their own)")
    ap.add_argument("--long-monolithic", action="store_true",
                    help="also replay the long-context trace with "
                         "prefill_chunk=0 and report the contrast "
                         "interference (slow at 16k prompts: one "
                         "prompt-sized forward)")
    ap.add_argument("--sp-capacity", type=int, default=0,
                    help="with the long-context replay: prove a context "
                         "longer than one device's page budget serves on "
                         "an N-device cpu sp submesh (geometry-tiny; "
                         "needs XLA_FLAGS host_platform_device_count)")
    args = ap.parse_args()

    import jax

    # honor JAX_PLATFORMS=cpu even when a sitecustomize pre-imported jax
    # with a TPU plugin registered (env vars are read at import time;
    # jax.config still works until a backend initializes — the same recipe
    # as tests/conftest.py / parallel.launch.initialize)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
        import re as _re
        m = _re.search(r"host_platform_device_count=(\d+)",
                       os.environ.get("XLA_FLAGS", ""))
        if m:
            from tpu_dist._compat import set_cpu_device_count
            set_cpu_device_count(int(m.group(1)))

    import jax.numpy as jnp
    import numpy as np

    from tpu_dist.engine.generate import generate
    from tpu_dist.models.transformer import TransformerLM

    lc_trace = None
    if args.long_context:
        with open(args.long_context) as f:
            lc_trace = json.load(f)
    long_mode = lc_trace is not None or bool(args.prompt_len_dist)

    total = args.prompt_len + args.steps
    # the pos_emb table must cover the longest sequence either mode runs:
    # the one-shot geometry AND the trace replay's worst case
    max_len = max(total, (args.max_prompt + args.max_out
                          + (args.prefix_len if args.prefix_tenants else 0))
                  if args.trace else 0)
    if long_mode:
        if lc_trace is not None:
            lc_max = max(r["prompt_len"] + r["out_len"]
                         for r in lc_trace["requests"])
        else:
            lens = [int(p.split(":")[0])
                    for p in args.prompt_len_dist.split(",")]
            lc_max = max(lens) + args.max_out
        max_len = max(max_len, lc_max, 8 * args.sp_capacity)
    dtype = jnp.bfloat16 if args.precision == "bf16" else jnp.float32
    if args.num_experts:
        from tpu_dist.models.moe import MoETransformerLM
        if args.trace:
            raise SystemExit("--trace serves the dense TransformerLM "
                             "(engine.serve has no MoE scheduling story "
                             "yet, ROADMAP item 4)")
        model = MoETransformerLM(
            vocab_size=args.vocab_size, num_layers=args.num_layers,
            d_model=args.d_model, num_heads=args.num_heads, max_len=max_len,
            num_experts=args.num_experts,
            capacity_factor=args.capacity_factor, dtype=dtype)
    else:
        model = TransformerLM(
            vocab_size=args.vocab_size, num_layers=args.num_layers,
            d_model=args.d_model, num_heads=args.num_heads, max_len=max_len,
            dtype=dtype)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 16), np.int32), train=False)["params"]
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, args.vocab_size, (args.batch, args.prompt_len)),
        jnp.int32)

    mesh = None
    if args.tp or args.dp:
        from tpu_dist.parallel.mesh import make_mesh
        if args.dp and args.batch % args.dp:
            # generate() would silently fall back to a replicated buffer
            # and the JSON would claim a dp run that never happened
            raise SystemExit(f"--dp {args.dp} needs --batch divisible by it "
                             f"(got {args.batch})")
        if args.tp and args.dp:
            mesh = make_mesh((args.dp, args.tp), ("data", "model"),
                             devices=jax.devices()[:args.dp * args.tp])
        elif args.tp:
            mesh = make_mesh((args.tp,), ("model",),
                             devices=jax.devices()[:args.tp])
        else:
            mesh = make_mesh((args.dp,), ("data",),
                             devices=jax.devices()[:args.dp])

    def timed(use_cache):
        # completion forced with a device_get readback — block_until_ready
        # does not reliably block across tunneled controllers (same caveat
        # as bench.py); the readback is (B, total) i32, microseconds.
        # ticks: the cache path runs ONE batched prefill forward + steps-1
        # one-token ticks; the full path runs exactly `steps` full forwards.
        ticks = args.steps
        out = generate(model, params, prompt, args.steps,
                       temperature=args.temperature, use_cache=use_cache,
                       top_k=args.top_k, top_p=args.top_p, mesh=mesh)
        jax.device_get(out)                             # compile + warm
        best = float("inf")
        for _ in range(args.trials):
            t0 = time.perf_counter()
            out = generate(model, params, prompt, args.steps,
                           temperature=args.temperature, use_cache=use_cache,
                           top_k=args.top_k, top_p=args.top_p, mesh=mesh)
            jax.device_get(out)
            best = min(best, time.perf_counter() - t0)
        toks = args.batch * args.steps
        return toks / best, best / ticks * 1e3, out

    ledger = None
    if args.ledger:
        from tpu_dist.obs.ledger import Ledger
        ledger = Ledger(args.ledger)
        ledger.emit("run_start", kind="decode_bench",
                    config={k: v for k, v in vars(args).items()
                            if not callable(v)},
                    mesh=({"tp": args.tp, "dp": args.dp}
                          if args.tp or args.dp else None),
                    devices=sorted({d.device_kind
                                    for d in jax.local_devices()}),
                    process_count=jax.process_count())

    cache_rate = None
    full_rate = None
    if not long_mode:
        cache_rate, cache_ms, out_c = timed(True)
        print(f"kv-cache decode: {cache_rate:,.0f} generated-tok/s incl. "
              f"batched prefill ({cache_ms:.2f} ms/generated token, "
              f"batch {args.batch}, {args.num_layers}L/d{args.d_model}, "
              f"prompt {args.prompt_len}, total {total})", file=sys.stderr)
    if not long_mode and not args.skip_full:
        full_rate, full_ms, out_f = timed(False)
        print(f"full-recompute decode: {full_rate:,.0f} tok/s "
              f"({full_ms:.2f} ms/token-tick)", file=sys.stderr)
        if args.temperature == 0.0:
            # with RANDOM weights the 32k-way logits are near-ties, so
            # bf16 rounding differences between the two attention orders
            # can break argmax differently and the sequences diverge —
            # exact equality on trained/tiny models is pinned by
            # tests/test_generate.py; this line is informational
            same = bool(jnp.array_equal(out_c, out_f))
            print(f"greedy outputs identical: {same} "
                  f"(random-weight near-ties; see tests/test_generate.py "
                  f"for the exact-equality contract)", file=sys.stderr)

    # -- per-request serving latency (the first scrape-able serving SLO):
    # N sequential warm kv-cache calls, each timed as one request; the
    # nearest-rank percentiles match tools/ledger_report.decode_section
    latency = None
    req_tok_s = None
    if not long_mode and args.requests > 0:
        lat = []
        for _ in range(args.requests):
            t0 = time.perf_counter()
            out_r = generate(model, params, prompt, args.steps,
                             temperature=args.temperature, use_cache=True,
                             top_k=args.top_k, top_p=args.top_p, mesh=mesh,
                             ledger=ledger)
            jax.device_get(out_r)  # completion forced (same tunnel caveat)
            lat.append(time.perf_counter() - t0)
        lat.sort()
        pick = lambda q: lat[min(int(round(q / 100.0 * (len(lat) - 1))),
                                 len(lat) - 1)]
        latency = {"p50_ms": round(pick(50) * 1e3, 3),
                   "p99_ms": round(pick(99) * 1e3, 3)}
        req_tok_s = round(args.batch * args.steps * len(lat) / sum(lat), 1)
        print(f"requests: {len(lat)} sequential kv-cache calls, "
              f"{req_tok_s:,.0f} tok/s; latency p50 {latency['p50_ms']:.1f}"
              f"ms / p99 {latency['p99_ms']:.1f}ms", file=sys.stderr)
    # -- request-trace replay (continuous batching vs static, engine.serve)
    serving = None
    if long_mode:
        serving = replay_long_context(args, model, params, trace=lc_trace)
    elif args.trace > 0:
        serving = replay_serving_trace(args, model, params, ledger=ledger)

    if ledger is not None:
        ledger.emit("run_end", steps=args.requests,
                    seconds=round(sum(lat), 3) if latency else 0.0)
        ledger.close()

    print(json.dumps({
        # long-context replays publish their own metric name so the
        # virtual-clock numbers never gate the wall-clock tok/s line
        # (the same convention as quant/tp_impl variants in bench.py)
        "metric": ("lm_longcontext_serving" if long_mode
                   else "lm_decode_tokens_per_sec"),
        "kv_cache": round(cache_rate, 1) if cache_rate is not None else None,
        "full_recompute": (round(full_rate, 1)
                           if full_rate is not None else None),
        "batch": args.batch, "prompt_len": args.prompt_len,
        "steps": args.steps, "layers": args.num_layers,
        "d_model": args.d_model, "vocab": args.vocab_size,
        "precision": args.precision,
        "temperature": args.temperature, "top_k": args.top_k,
        "top_p": args.top_p, "tp": args.tp, "dp": args.dp,
        "num_experts": args.num_experts,
        "requests": args.requests or None,
        "latency_ms": latency,
        "request_tokens_per_sec": req_tok_s,
        "serving": serving,
        "ledger": args.ledger or None,
    }))


if __name__ == "__main__":
    main()
