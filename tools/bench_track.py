#!/usr/bin/env python
"""Bench regression tracker over the checked-in BENCH_r*.json history.

    python tools/bench_track.py                    # trend table (repo root)
    python tools/bench_track.py --check            # CI gate: nonzero on drop
    python tools/bench_track.py --json             # machine-readable
    python tools/bench_track.py --headline out.json  # + this run's headline

Every round of this repo drops a ``BENCH_r<N>.json`` (the bench driver's
wrapper: ``{"n", "cmd", "rc", "tail", "parsed": {metric, value, unit,
mfu, ...}}``) — five rounds of history that, until now, nothing read. This
tool turns them into a guarded trajectory: a per-metric trend table
(value, Δ%, MFU per round) and a threshold check that FAILS when the
newest point drops more than ``--threshold-pct`` below the trailing best
of its metric — the reference cookbook's apex ``data_prefetcher`` bug
(PAPER.md) was exactly a silent per-round regression this would have
caught at review time.

Accepted inputs per file (positional args override the default glob):
the wrapper format above, or a raw headline JSON object (``{"metric",
"value", ...}`` — what ``bench.py`` prints) via ``--headline`` for the
run-under-test. Different metric names track independently (quant/tp_impl
variants publish their own names by design — bench.py), so a variant run
never gates the bf16 headline. Stdlib only: runs in CI, on a login host,
anywhere.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_points(paths: List[str], out_err=None) -> List[dict]:
    """[{metric, value, round, file, unit, mfu, vs_baseline}] from wrapper
    and raw-headline files alike; files with no parseable metric (failed
    rounds, MULTICHIP dryruns) are skipped with a note."""
    out_err = out_err or (lambda s: print(s, file=sys.stderr))
    points = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            out_err(f"bench_track: skipping {path}: {e}")
            continue
        if not isinstance(doc, dict):
            out_err(f"bench_track: skipping {path}: not a JSON object")
            continue
        parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
            else (doc if "metric" in doc else None)
        if parsed is not None and "value" not in parsed \
                and "kv_cache" in parsed:
            # decode_bench headline: the kv-cache tok/s IS the value (and
            # round 11's serving replay block rides the same object). The
            # long-context replay (round 19) skips the one-shot sections
            # entirely (kv_cache: null) — its completed-requests-per-tick
            # is the value, under its own metric name
            v = parsed["kv_cache"]
            if v is None and isinstance(parsed.get("serving"), dict):
                v = parsed["serving"].get("requests_per_tick")
            parsed = dict(parsed, value=v)
        if not parsed or "metric" not in parsed or "value" not in parsed:
            out_err(f"bench_track: skipping {path}: no parsed metric "
                    "(failed round or non-bench file)")
            continue
        try:
            value = float(parsed["value"])
        except (TypeError, ValueError):
            # a crashed round can leave value: null — skip, don't die
            out_err(f"bench_track: skipping {path}: non-numeric value "
                    f"{parsed['value']!r}")
            continue
        rnd = doc.get("n")
        if rnd is None:
            m = re.search(r"_r0*(\d+)\.json$", os.path.basename(path))
            rnd = int(m.group(1)) if m else None
        phases = parsed.get("phases") if isinstance(parsed.get("phases"),
                                                    dict) else {}
        # pre-round-9 headlines hardcoded data_s: 0.0 (device-resident
        # bench, no measurement); a real measured wait never rounds to
        # exactly 0 — treat the placeholder as absent so the gate judges
        # measured-vs-measured, never measured-vs-synthetic
        data_s = phases.get("data_s")
        if data_s == 0:
            data_s = None
        # serving trace replay (decode_bench --trace, round 11+): the
        # deterministic completed-requests-per-tick is the gated number —
        # wall req/s rides the same block but carries machine variance
        serving = (parsed.get("serving")
                   if isinstance(parsed.get("serving"), dict) else {})
        # fleet simulation (tpu_dist.sim, round 14+): the stitched fleet
        # goodput ratio is the gated end-to-end number; history without a
        # fleet block abstains, exactly the data_s/serving convention
        fleet = (parsed.get("fleet")
                 if isinstance(parsed.get("fleet"), dict) else {})
        # tuned step plans (tpu_dist.plan, round 15+): a headline driven
        # by BENCH_PLAN carries a plan block — its metric tracks under a
        # [plan:<hash>]-tagged name so plan-tuned runs gate against THEIR
        # OWN history and pre-plan points abstain, exactly the quant/
        # tp_impl naming convention (variants never gate the bf16 line)
        plan = (parsed.get("plan")
                if isinstance(parsed.get("plan"), dict) else None)
        metric = parsed["metric"]
        if plan and plan.get("hash"):
            metric = f"{metric}[plan:{plan['hash']}]"
        points.append({
            "metric": metric,
            "value": value,
            "unit": parsed.get("unit"),
            "mfu": parsed.get("mfu"),
            "vs_baseline": parsed.get("vs_baseline"),
            "data_s": data_s,
            "serving_rpt": serving.get("requests_per_tick"),
            # round 16+: speculative acceptance (higher is better) and
            # fresh pages per request (LOWER is better — the prefix
            # cache's number); pre-spec history abstains like the rest
            "serving_apt": serving.get("accepted_per_tick"),
            "serving_ppr": serving.get("pages_per_request"),
            # round 17+: attribution coverage from the request spans —
            # the share of completed-request latency the queue/prefill/
            # decode spans account for (1.0 on any ledger that lost no
            # span); pre-span history abstains like the rest
            "serving_cov": (serving.get("tail_attribution") or {}).get(
                "coverage") if isinstance(
                serving.get("tail_attribution"), dict) else None,
            # round 19+: the long-context replay's virtual-clock tail
            # numbers, both LOWER is better — TTFT p99 of the >=threshold
            # prompts, and short-request TPOT degradation vs the
            # no-long-prompt baseline; pre-long-context history abstains
            "serving_ttfl": serving.get("ttft_long_p99"),
            "serving_tip": serving.get("tpot_interference_pct"),
            "fleet_goodput": fleet.get("goodput_ratio"),
            # round 20+: autoscale reaction time — ticks from burst onset
            # to the first up decision, LOWER is better; pre-autoscale
            # history carries no field and abstains like the rest
            "fleet_lag": fleet.get("autoscale_lag_ticks"),
            "round": rnd,
            "file": os.path.basename(path),
        })
    # order by round where known (unknown rounds sort last, in arg order —
    # the --headline run-under-test lands there as the newest point)
    points.sort(key=lambda p: (p["round"] is None, p["round"] or 0))
    return points


def track(points: List[dict], threshold_pct: float,
          data_s_slack: float = 0.05) -> dict:
    """Group points by metric and judge the newest against the trailing
    best: {'metrics': {name: {...}}, 'ok': bool}.

    Beside the headline value, the newest point's ``data_s`` (the bench's
    best-trial input wait, headline JSON ``phases.data_s``) is judged
    against the best (lowest) prior: a rise of more than ``data_s_slack``
    seconds fails the gate even when throughput still looks fine — the
    apex-prefetcher class of bug where the input pipeline silently stops
    overlapping but a compute-bound trial hides it for one more round.
    Points without phases (pre-round-6 history) abstain rather than judge.
    """
    by_metric: dict = {}
    for p in points:
        by_metric.setdefault(p["metric"], []).append(p)
    report = {"metrics": {}, "ok": True, "threshold_pct": threshold_pct,
              "data_s_slack": data_s_slack}
    for name, series in by_metric.items():
        latest = series[-1]
        prior = series[:-1]
        best_prior = max((p["value"] for p in prior), default=None)
        drop_pct = None
        regressed = False
        if best_prior:
            drop_pct = (best_prior - latest["value"]) / best_prior * 100.0
            regressed = drop_pct > threshold_pct
        prior_data = [p["data_s"] for p in prior
                      if p.get("data_s") is not None]
        data_best = min(prior_data, default=None)
        data_regressed = (data_best is not None
                          and latest.get("data_s") is not None
                          and latest["data_s"] > data_best + data_s_slack)
        # serving throughput-under-load: judged like the headline value
        # (higher is better, threshold_pct) against the best prior point
        # that CARRIES a serving block — pre-serving history abstains,
        # exactly the data_s convention
        prior_srv = [p["serving_rpt"] for p in prior
                     if p.get("serving_rpt") is not None]
        srv_best = max(prior_srv, default=None)
        srv_latest = latest.get("serving_rpt")
        srv_regressed = (srv_best is not None and srv_latest is not None
                         and (srv_best - srv_latest) / srv_best * 100.0
                         > threshold_pct)
        # speculative acceptance (round 16+): higher is better, same
        # abstention convention (pre-spec history carries no field)
        prior_apt = [p["serving_apt"] for p in prior
                     if p.get("serving_apt") is not None]
        apt_best = max(prior_apt, default=None)
        apt_latest = latest.get("serving_apt")
        apt_regressed = (apt_best is not None and apt_latest is not None
                         and (apt_best - apt_latest) / apt_best * 100.0
                         > threshold_pct)
        # fresh pages per request (round 16+): LOWER is better — the gate
        # reverses (judged against the best = lowest prior, fails on RISE)
        prior_ppr = [p["serving_ppr"] for p in prior
                     if p.get("serving_ppr") is not None]
        ppr_best = min(prior_ppr, default=None)
        ppr_latest = latest.get("serving_ppr")
        ppr_regressed = (ppr_best is not None and ppr_latest is not None
                         and ppr_best > 0
                         and (ppr_latest - ppr_best) / ppr_best * 100.0
                         > threshold_pct)
        # attribution coverage (round 17+): higher is better (1.0 means
        # every completed request's latency fully decomposes into spans);
        # a drop means the engine started losing span windows
        prior_cov = [p["serving_cov"] for p in prior
                     if p.get("serving_cov") is not None]
        cov_best = max(prior_cov, default=None)
        cov_latest = latest.get("serving_cov")
        cov_regressed = (cov_best is not None and cov_latest is not None
                         and (cov_best - cov_latest) / cov_best * 100.0
                         > threshold_pct)
        # long-context TTFT p99 (round 19+): LOWER is better, virtual
        # token-equivalent units — judged like pages_per_request against
        # the best (lowest) prior carrying the field, fails on RISE
        prior_ttfl = [p["serving_ttfl"] for p in prior
                      if p.get("serving_ttfl") is not None]
        ttfl_best = min(prior_ttfl, default=None)
        ttfl_latest = latest.get("serving_ttfl")
        ttfl_regressed = (ttfl_best is not None and ttfl_latest is not None
                          and ttfl_best > 0
                          and (ttfl_latest - ttfl_best) / ttfl_best * 100.0
                          > threshold_pct)
        # long-context TPOT interference (round 19+): LOWER is better and
        # already a percentage — judged on ABSOLUTE percentage points
        # (threshold_pct of them), since the best prior can sit near zero
        prior_tip = [p["serving_tip"] for p in prior
                     if p.get("serving_tip") is not None]
        tip_best = min(prior_tip, default=None)
        tip_latest = latest.get("serving_tip")
        tip_regressed = (tip_best is not None and tip_latest is not None
                         and tip_latest > tip_best + threshold_pct)
        # fleet goodput ratio (tpu_dist.sim): higher is better, judged
        # against the best prior point CARRYING a fleet block — pre-fleet
        # history abstains, exactly the data_s/serving convention
        prior_fleet = [p["fleet_goodput"] for p in prior
                       if p.get("fleet_goodput") is not None]
        fleet_best = max(prior_fleet, default=None)
        fleet_latest = latest.get("fleet_goodput")
        fleet_regressed = (fleet_best is not None
                           and fleet_latest is not None
                           and (fleet_best - fleet_latest) / fleet_best
                           * 100.0 > threshold_pct)
        # autoscale reaction lag (round 20+): LOWER is better — judged
        # against the best (lowest) prior carrying the field, fails on
        # RISE; a zero best prior abstains (no relative scale to judge)
        prior_lag = [p["fleet_lag"] for p in prior
                     if p.get("fleet_lag") is not None]
        lag_best = min(prior_lag, default=None)
        lag_latest = latest.get("fleet_lag")
        lag_regressed = (lag_best is not None and lag_latest is not None
                         and lag_best > 0
                         and (lag_latest - lag_best) / lag_best * 100.0
                         > threshold_pct)
        rounds = [{"round": p["round"], "value": p["value"],
                   "mfu": p["mfu"], "file": p["file"],
                   "data_s": p.get("data_s"),
                   "delta_pct": (None if i == 0 or not series[i - 1]["value"]
                                 else (p["value"] / series[i - 1]["value"]
                                       - 1.0) * 100.0)}
                  for i, p in enumerate(series)]
        report["metrics"][name] = {
            "unit": latest["unit"], "rounds": rounds,
            "latest": latest["value"], "best_prior": best_prior,
            "drop_pct": drop_pct, "regressed": regressed,
            "data_s_latest": latest.get("data_s"),
            "data_s_best_prior": data_best,
            "data_s_regressed": data_regressed,
            "serving_latest": srv_latest,
            "serving_best_prior": srv_best,
            "serving_regressed": srv_regressed,
            "accepted_latest": apt_latest,
            "accepted_best_prior": apt_best,
            "accepted_regressed": apt_regressed,
            "pages_latest": ppr_latest,
            "pages_best_prior": ppr_best,
            "pages_regressed": ppr_regressed,
            "coverage_latest": cov_latest,
            "coverage_best_prior": cov_best,
            "coverage_regressed": cov_regressed,
            "fleet_latest": fleet_latest,
            "fleet_best_prior": fleet_best,
            "fleet_regressed": fleet_regressed,
            "autoscale_lag_latest": lag_latest,
            "autoscale_lag_best_prior": lag_best,
            "autoscale_lag_regressed": lag_regressed,
            "ttft_long_latest": ttfl_latest,
            "ttft_long_best_prior": ttfl_best,
            "ttft_long_regressed": ttfl_regressed,
            "interference_latest": tip_latest,
            "interference_best_prior": tip_best,
            "interference_regressed": tip_regressed,
        }
        if (regressed or data_regressed or srv_regressed or apt_regressed
                or ppr_regressed or cov_regressed or fleet_regressed
                or ttfl_regressed or tip_regressed or lag_regressed):
            report["ok"] = False
    return report


def render(report: dict, out=print) -> None:
    for name, m in sorted(report["metrics"].items()):
        out(f"{name} ({m['unit'] or '?'}):")
        for r in m["rounds"]:
            rnd = f"r{r['round']:02d}" if r["round"] is not None else "head"
            out(f"  {rnd}  {r['value']:>12,.1f}"
                + (f"  {r['delta_pct']:+6.1f}%" if r["delta_pct"] is not None
                   else "   " + " " * 6)
                + (f"  MFU {r['mfu'] * 100:.1f}%" if r.get("mfu") else "")
                + f"  [{r['file']}]")
        if m["best_prior"] is not None:
            verdict = (f"REGRESSED {m['drop_pct']:.1f}% below trailing best "
                       f"{m['best_prior']:,.1f} (threshold "
                       f"{report['threshold_pct']:g}%)"
                       if m["regressed"] else
                       f"ok: latest {m['latest']:,.1f} vs trailing best "
                       f"{m['best_prior']:,.1f} "
                       f"({-m['drop_pct']:+.1f}%)")
            out(f"  -> {verdict}")
        else:
            out("  -> single point; nothing to judge")
        if m.get("data_s_best_prior") is not None \
                and m.get("data_s_latest") is not None:
            verdict = ("DATA_S REGRESSED" if m["data_s_regressed"] else "ok")
            out(f"  -> data_s {verdict}: latest {m['data_s_latest']:.4f}s "
                f"vs best prior {m['data_s_best_prior']:.4f}s (slack "
                f"{report['data_s_slack']:g}s)")
        if m.get("serving_latest") is not None:
            if m.get("serving_best_prior") is not None:
                verdict = ("SERVING REGRESSED" if m["serving_regressed"]
                           else "ok")
                out(f"  -> serving {verdict}: latest "
                    f"{m['serving_latest']:.4f} req/tick vs best prior "
                    f"{m['serving_best_prior']:.4f} (threshold "
                    f"{report['threshold_pct']:g}%)")
            else:
                out(f"  -> serving: {m['serving_latest']:.4f} req/tick "
                    "(no prior serving history; nothing to judge)")
        if m.get("accepted_latest") is not None:
            if m.get("accepted_best_prior") is not None:
                verdict = ("ACCEPTANCE REGRESSED"
                           if m["accepted_regressed"] else "ok")
                out(f"  -> spec {verdict}: {m['accepted_latest']:.4f} "
                    f"accepted/tick vs best prior "
                    f"{m['accepted_best_prior']:.4f} (threshold "
                    f"{report['threshold_pct']:g}%)")
            else:
                out(f"  -> spec: {m['accepted_latest']:.4f} accepted/tick "
                    "(no prior speculative history; nothing to judge)")
        if m.get("pages_latest") is not None:
            if m.get("pages_best_prior") is not None:
                verdict = ("PAGES REGRESSED" if m["pages_regressed"]
                           else "ok")
                out(f"  -> pages {verdict}: {m['pages_latest']:.4f} "
                    f"fresh pages/request vs best (lowest) prior "
                    f"{m['pages_best_prior']:.4f} (threshold "
                    f"{report['threshold_pct']:g}%, lower is better)")
            else:
                out(f"  -> pages: {m['pages_latest']:.4f} fresh "
                    "pages/request (no prior prefix-cache history; "
                    "nothing to judge)")
        if m.get("coverage_latest") is not None:
            if m.get("coverage_best_prior") is not None:
                verdict = ("COVERAGE REGRESSED"
                           if m["coverage_regressed"] else "ok")
                out(f"  -> attribution {verdict}: coverage "
                    f"{m['coverage_latest']:.4f} vs best prior "
                    f"{m['coverage_best_prior']:.4f} (threshold "
                    f"{report['threshold_pct']:g}%)")
            else:
                out(f"  -> attribution: coverage "
                    f"{m['coverage_latest']:.4f} (no prior span history; "
                    "nothing to judge)")
        if m.get("ttft_long_latest") is not None:
            if m.get("ttft_long_best_prior") is not None:
                verdict = ("TTFT-LONG REGRESSED"
                           if m["ttft_long_regressed"] else "ok")
                out(f"  -> ttft-long {verdict}: p99 "
                    f"{m['ttft_long_latest']:,.1f} virtual tok-equiv vs "
                    f"best (lowest) prior {m['ttft_long_best_prior']:,.1f} "
                    f"(threshold {report['threshold_pct']:g}%, lower is "
                    "better)")
            else:
                out(f"  -> ttft-long: p99 {m['ttft_long_latest']:,.1f} "
                    "virtual tok-equiv (no prior long-context history; "
                    "nothing to judge)")
        if m.get("interference_latest") is not None:
            if m.get("interference_best_prior") is not None:
                verdict = ("INTERFERENCE REGRESSED"
                           if m["interference_regressed"] else "ok")
                out(f"  -> interference {verdict}: short-TPOT "
                    f"{m['interference_latest']:+.2f}% vs best (lowest) "
                    f"prior {m['interference_best_prior']:+.2f}% "
                    f"(slack {report['threshold_pct']:g} percentage "
                    "points, lower is better)")
            else:
                out(f"  -> interference: short-TPOT "
                    f"{m['interference_latest']:+.2f}% (no prior "
                    "long-context history; nothing to judge)")
        if m.get("fleet_latest") is not None:
            if m.get("fleet_best_prior") is not None:
                verdict = ("FLEET REGRESSED" if m["fleet_regressed"]
                           else "ok")
                out(f"  -> fleet {verdict}: goodput ratio "
                    f"{m['fleet_latest']:.4f} vs best prior "
                    f"{m['fleet_best_prior']:.4f} (threshold "
                    f"{report['threshold_pct']:g}%)")
            else:
                out(f"  -> fleet: goodput ratio {m['fleet_latest']:.4f} "
                    "(no prior fleet history; nothing to judge)")
        if m.get("autoscale_lag_latest") is not None:
            if m.get("autoscale_lag_best_prior") is not None:
                verdict = ("AUTOSCALE-LAG REGRESSED"
                           if m["autoscale_lag_regressed"] else "ok")
                out(f"  -> autoscale {verdict}: lag "
                    f"{m['autoscale_lag_latest']:.1f} tick(s) vs best "
                    f"(lowest) prior {m['autoscale_lag_best_prior']:.1f} "
                    f"(threshold {report['threshold_pct']:g}%, lower is "
                    "better)")
            else:
                out(f"  -> autoscale: lag {m['autoscale_lag_latest']:.1f} "
                    "tick(s) (no prior autoscale history; nothing to "
                    "judge)")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*",
                    help="bench JSONs (default: <repo>/BENCH_r*.json)")
    ap.add_argument("--dir", default=ROOT,
                    help="directory holding BENCH_r*.json (default: repo "
                    "root)")
    ap.add_argument("--headline", default="",
                    help="a raw bench.py headline JSON for the run under "
                    "test, appended as the newest point")
    ap.add_argument("--threshold-pct", type=float, default=5.0,
                    help="fail when the newest point drops more than this "
                    "%% below the metric's trailing best (default 5)")
    ap.add_argument("--data-s-slack", type=float, default=0.05,
                    help="fail when the newest point's phases.data_s rises "
                    "more than this many seconds above the metric's best "
                    "prior (input-pipeline regression gate; default 0.05)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any regressed metric (the CI gate; "
                    "implied by --headline)")
    ap.add_argument("--json", action="store_true",
                    help="print the report as one JSON object on stdout")
    args = ap.parse_args(argv)

    files = list(args.files) or sorted(
        glob.glob(os.path.join(args.dir, "BENCH_r*.json")))
    if args.headline:
        files.append(args.headline)
    if not files:
        print(f"bench_track: no BENCH_r*.json under {args.dir} and no "
              "files given", file=sys.stderr)
        return 2
    points = load_points(files)
    if not points:
        print("bench_track: no usable bench points", file=sys.stderr)
        return 2
    if args.headline and not any(p["file"] == os.path.basename(args.headline)
                                 for p in points):
        # the gate --headline implies must never silently judge only the
        # history: a missing/corrupt run-under-test is itself a failure
        print(f"bench_track: headline {args.headline} yielded no usable "
              "point — the run under test cannot be judged", file=sys.stderr)
        return 2
    report = track(points, args.threshold_pct,
                   data_s_slack=args.data_s_slack)
    if args.json:
        print(json.dumps(report))
    else:
        render(report)
    if (args.check or args.headline) and not report["ok"]:
        bad = [k for k, m in report["metrics"].items()
               if m["regressed"] or m.get("data_s_regressed")
               or m.get("serving_regressed") or m.get("accepted_regressed")
               or m.get("pages_regressed") or m.get("coverage_regressed")
               or m.get("fleet_regressed") or m.get("ttft_long_regressed")
               or m.get("interference_regressed")
               or m.get("autoscale_lag_regressed")]
        print(f"bench_track: REGRESSION in {bad}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
