#!/usr/bin/env python
"""Capture + attribute an XLA profile of the LM bench step (the round-5
image-profile methodology — tools/profile_image.py — applied to the LM
flagship, so its MFU gap is attributed rather than asserted).

Builds the EXACT windowed step bench.py's lm_bench times (ONE shared
builder, bench.lm_build — every BENCH_* knob including BENCH_OPTIMIZER,
BENCH_STEPS_PER_WINDOW and BENCH_LOSS_CHUNK behaves identically), captures
a device trace with jax.profiler, and post-processes the xplane with
xprof's converter into per-op-type device-time tables. Usage:

    python tools/profile_lm.py [out_dir]          # default /tmp/lmprof
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from profile_image import attribute, find_xplane, op_table  # noqa: E402


def capture(out_dir: str):
    import jax

    import bench

    b = bench.lm_build()
    window, state = b["window"], b["state"]
    rows_dev, idx_dev, key = b["rows_dev"], b["idx_dev"], b["key"]
    batch, L, k = b["batch"], b["L"], b["k"]

    state, m = window(state, rows_dev, idx_dev, key)    # compile + warm
    jax.device_get(m)
    t0 = time.perf_counter()
    with jax.profiler.trace(out_dir):
        state, m = window(state, rows_dev, idx_dev, key)
        jax.device_get(m)                               # tunnel readback
    wall = time.perf_counter() - t0
    print(f"captured: {k}-step window, batch {batch}, L {L}, wall "
          f"{wall:.3f}s -> {batch * k * L / wall:,.0f} tok/s",
          file=sys.stderr)
    return batch * L, k


def main():
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/lmprof"
    if os.environ.get("PROFILE_PARSE_ONLY") != "1":
        tokens, k = capture(out_dir)
    else:
        # the SAME geometry parse the capture used (bench.lm_geometry) so a
        # parse-only rerun normalizes the trace to identical numbers
        import bench
        g = bench.lm_geometry()
        tokens, k = g["batch"] * g["L"], g["k"]
    xp = find_xplane(out_dir)
    print(f"xplane: {xp}", file=sys.stderr)
    rows = op_table(xp)
    attribute(rows, k, tokens, unit="tok")


if __name__ == "__main__":
    main()
