#!/usr/bin/env python
"""Summarize a tpu_dist run ledger (obs.ledger JSONL) from the CLI.

    python tools/ledger_report.py run.jsonl            # summary
    python tools/ledger_report.py run.jsonl --tail 20  # + last N step lines
    python tools/ledger_report.py run.jsonl --json     # machine-readable

Renders: run identity (kind/mesh/devices/processes), per-phase time share
(data wait vs dispatch vs device block across every step record), the
goodput section (obs.goodput: wall-clock partitioned into goodput and the
badput categories — startup/compile, data wait, dispatch, eval, ckpt,
stalls, health-skipped steps, idle residue, restart gaps — summing to
100% of the stitched wall), the
roofline section (obs.attr cost-model buckets vs measured device/comm
seconds and MFU — where the non-MFU time goes), MFU and throughput trend
(first/middle/last thirds), the epoch table, the decode/serving section
(per-request latency p50/p99 + tok/s over `decode` events),
cross-host skew/straggler
summary, numerical-health trips (obs.health), flight-recorder diagnosis
bundles (obs.flightrec), and any watchdog stall dumps; multi-process runs
get a pointer at the merged Chrome trace (tools/trace_merge.py).
Restart-attempt sibling ledgers (``run.a1.jsonl``, ... — obs.goodput run
lineage) are auto-discovered and stitched into one job timeline, with the
between-attempt gaps charged as ``restart_gap`` badput (``--no-discover``
reads only the given file). ``--json``
prints the same summary as one JSON object (the stable input for
dashboards and the ROADMAP auto-tuner). Corrupt/truncated trailing lines —
crashed runs are exactly the ones inspected here — are skipped with a
warning, never a crash. Pure stdlib + the ledger module — safe to run on
a login host with no jax installed (obs.ledger imports nothing heavy).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_dist.obs.ledger import ProgressSink, phase_totals  # noqa: E402


def _mean(xs):
    xs = list(xs)
    return sum(xs) / len(xs) if xs else None


def _fmt_mfu(x):
    return f"{x * 100:.1f}%" if x is not None else "n/a"


def _num(v, spec):
    """None-tolerant numeric cell ('?' for a schema-legal null)."""
    return f"{v:{spec}}" if v is not None else "?"


def _thirds(xs):
    """(first, middle, last) third means — the cheap trend view."""
    if not xs:
        return None, None, None
    n = max(len(xs) // 3, 1)
    return _mean(xs[:n]), _mean(xs[len(xs) // 2 - n // 2:
                                   len(xs) // 2 - n // 2 + n]), _mean(xs[-n:])


def _si(x, unit=""):
    """Engineering-format a count (1.23 G, 45.6 M ...)."""
    if x is None:
        return "?"
    for div, suf in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(x) >= div:
            return f"{x / div:.2f} {suf}{unit}"
    return f"{x:.0f} {unit}" if unit else f"{x:.0f}"


def roofline(cost_models, hot, mfu_mean=None, out=print):
    """The cost-model-vs-measured section: per-category flop/byte shares
    with ideal (roofline) seconds per optimizer step, against the
    measured per-step device block and comm estimate. ``hot`` is the
    warm-excluded step list summarize() already built (one filtering
    rule, not two). Returns the machine-readable dict (also embedded in
    --json output).

    The cost model counts a scan (window) body ONCE, so its totals read
    as one optimizer step — the same per-step units the measured side is
    divided down to."""
    if not cost_models:
        return None
    cm = cost_models[-1]  # the last compile is the program that trained
    buckets = cm.get("buckets") or {}
    if not buckets:
        return None
    peak_tf = cm.get("peak_tflops") or 0.0
    peak_gb = cm.get("peak_gbps") or 0.0
    tot_f = cm.get("total_flops") or sum(
        b.get("flops") or 0 for b in buckets.values())
    tot_b = cm.get("total_bytes") or sum(
        b.get("bytes") or 0 for b in buckets.values())
    nominal = bool(cm.get("peak_is_nominal"))

    def ideal_s(flops, nbytes):
        t_c = flops / (peak_tf * 1e12) if peak_tf else None
        t_m = nbytes / (peak_gb * 1e9) if peak_gb else None
        if t_c is None and t_m is None:
            return None, "?"
        if (t_c or 0) >= (t_m or 0):
            return t_c, "compute"
        return t_m, "memory"

    n_opt = sum(r.get("steps_in_dispatch") or 1 for r in hot) or 1
    dev_s = sum(r.get("device_s") or 0 for r in hot) / n_opt
    comm_s = sum(r.get("comm_s") or 0 for r in hot) / n_opt
    mfu = mfu_mean

    out(f"\nroofline (cost model vs measured, program "
        f"{cm.get('program')!r}"
        + (", NOMINAL peaks" if nominal else "") + "):")
    out(f"  {'category':<26} {'flops%':>7} {'bytes%':>7} "
        f"{'ideal s/step':>13}  bound")
    rows = {}
    for cat in sorted(buckets, key=lambda c: -(buckets[c].get("flops") or 0)):
        b = buckets[cat]
        f, by = b.get("flops") or 0.0, b.get("bytes") or 0.0
        t, bound = ideal_s(f, by)
        if cat.startswith("collective:"):
            bound = "comm"
        rows[cat] = {"flops": f, "bytes": by, "flops_share":
                     f / tot_f if tot_f else None,
                     "bytes_share": by / tot_b if tot_b else None,
                     "ideal_s": t, "bound": bound}
        out(f"  {cat:<26} {f / tot_f * 100 if tot_f else 0:6.1f}% "
            f"{by / tot_b * 100 if tot_b else 0:6.1f}% "
            + (f"{t:13.3g}" if t is not None else f"{'?':>13}")
            + f"  {bound}")
    ideal_total, _ = ideal_s(tot_f, tot_b)
    coll_b = sum(b.get("bytes") or 0 for c, b in buckets.items()
                 if c.startswith("collective:"))
    out(f"  model total {_si(tot_f, 'FLOP')} + {_si(tot_b, 'B')} per step"
        + (f" -> ideal {ideal_total:.3g} s/step" if ideal_total else ""))
    gap = dev_s / ideal_total if ideal_total and dev_s else None
    if dev_s:
        out(f"  measured: device {dev_s:.3g} s/step"
            + ((f" = {gap:,.0f}x ideal" if gap >= 10 else
                f" = {gap:.2f}x ideal") if gap else "")
            + (f"; MFU {_fmt_mfu(mfu)} (mean)" if mfu is not None else ""))
    if comm_s and coll_b:
        out(f"  comm: measured {comm_s:.3g} s/step vs {_si(coll_b, 'B')} "
            f"collective -> {coll_b / comm_s / 1e9:.2f} GB/s effective")
    return {"program": cm.get("program"), "categories": rows,
            "total_flops": tot_f, "total_bytes": tot_b,
            "collective_bytes": coll_b, "ideal_s_per_step": ideal_total,
            "measured_device_s_per_step": dev_s or None,
            "measured_comm_s_per_step": comm_s or None,
            "gap_vs_ideal": gap, "mfu_mean": mfu,
            "peak_tflops": peak_tf or None, "peak_gbps": peak_gb or None,
            "peak_is_nominal": nominal}


def _pctl(xs, q):
    """Nearest-rank percentile of a sorted list (stdlib-only)."""
    if not xs:
        return None
    return xs[min(int(round(q / 100.0 * (len(xs) - 1))), len(xs) - 1)]


GOODPUT_LABELS = {"startup": "startup/compile", "data_wait": "data wait",
                  "dispatch": "dispatch", "eval": "eval",
                  "ckpt": "checkpoint", "stall": "watchdog stall",
                  "skipped": "health-skipped", "idle": "idle/drain",
                  "restart_gap": "restart gap"}


def goodput_section(records, out=print):
    """The accounting section (obs.goodput): goodput + badput categories
    over the (possibly multi-attempt) stitched wall-clock. Returns the
    machine-readable dict (rides in --json)."""
    from tpu_dist.obs.goodput import job_accounting, split_attempts

    attempts = split_attempts(records)
    gp = job_accounting(attempts)
    if gp is None or not gp["wall_s"]:
        return gp
    slo_events = [r for r in records if r["event"] == "slo"]
    gp["slo_breaches"] = len(slo_events)
    n_att = len(gp["attempts"])
    wall = gp["wall_s"]
    out(f"\ngoodput ({n_att} attempt(s), stitched wall {wall:.1f}s):")
    rows = [("goodput", gp["goodput_s"])] + [
        (cat, gp["categories"].get(cat, 0.0))
        for cat in GOODPUT_LABELS]
    for cat, secs in rows:
        if cat != "goodput" and not secs:
            continue  # only non-zero badput rows earn a line
        out(f"  {GOODPUT_LABELS.get(cat, cat):<16} {secs:9.3f}s  "
            f"{secs / wall * 100:5.1f}%")
    out(f"  goodput ratio {gp['ratio']:.3f} over {gp['opt_steps']} "
        f"optimizer steps"
        + (f"; OVERRUN {gp['overrun_s']:.3f}s double-attributed"
           if gp["overrun_s"] else ""))
    if n_att > 1:
        for a in gp["attempts"]:
            out(f"  attempt {a['attempt']}: {a['wall_s']:.1f}s wall, "
                f"{a['goodput_s']:.1f}s goodput, status "
                f"{a['status'] or 'MISSING run_end (killed?)'}"
                + (f", restart gap {a['restart_gap_s']:.1f}s before it"
                   if a["restart_gap_s"] else ""))
    if slo_events:
        last = slo_events[-1]
        out(f"  SLO: {len(slo_events)} breach(es); last: "
            f"{last.get('kind')} {last.get('value')} < floor "
            f"{last.get('floor')} at step {last.get('step')}")
    return gp


def restarts_section(records, out=print, crash_loop_k=3):
    """The remediation view of a stitched multi-attempt job: per-attempt
    failure classification (parallel.supervisor.classify_attempt over
    ``run_end`` status + ``fault``/``stall`` evidence), injected-vs-organic
    fault counts, and a crash-loop banner when the trailing
    ``crash_loop_k`` attempts all died before their first step. Rendered
    only when there is something to say (restarts or injections)."""
    from tpu_dist.obs.goodput import split_attempts
    from tpu_dist.parallel.supervisor import classify_attempt

    fault_events = [r for r in records if r["event"] == "fault"]
    attempts = split_attempts(records)
    if len(attempts) <= 1 and not fault_events:
        return None
    rows = []
    for recs in attempts:
        starts = [r for r in recs if r.get("event") == "run_start"]
        ordinal = (starts[0].get("attempt")
                   if starts and starts[0].get("attempt") is not None
                   else len(rows))
        rows.append({
            "attempt": ordinal,
            "class": classify_attempt(recs),
            "steps": sum(1 for r in recs if r.get("event") == "step"),
            "degraded": bool(starts and starts[0].get("degraded")),
            "processes": starts[0].get("process_count") if starts else None,
            "injected": [str(r.get("site") or "?") for r in recs
                         if r.get("event") == "fault"]})
    organic = sum(1 for r in rows
                  if r["class"] != "clean" and not r["injected"])
    out(f"\nrestarts ({len(rows)} attempt(s), "
        f"{len(rows) - 1} restart(s)):")
    for r in rows:
        out(f"  attempt {r['attempt']}: {r['class']}, "
            f"{r['steps']} step record(s)"
            + (f" [degraded mesh, {r['processes']} proc]" if r["degraded"]
               else "")
            + (f"; injected fault(s): {', '.join(r['injected'])}"
               if r["injected"] else ""))
    trailing_dead = 0
    for r in reversed(rows):
        if r["steps"] or r["class"] == "clean":
            break
        trailing_dead += 1
    crash_loop = trailing_dead >= crash_loop_k
    if crash_loop:
        out(f"  CRASH LOOP: the last {trailing_dead} attempts died before "
            "their first step — the failure is deterministic; fix the run "
            "instead of restarting it")
    out(f"  faults: {len(fault_events)} injected (obs.faults), "
        f"{organic} organic failure(s)")
    return {"attempts": rows, "injected_faults": len(fault_events),
            "organic_failures": organic, "crash_loop": crash_loop}


_SCALE_LABELS = {"shrink": "mesh shrink", "expand": "mesh re-expansion",
                 "preempt_snapshot": "preemption snapshot",
                 "peer_restore": "peer state restore",
                 "drain": "serve drain"}


def elasticity_section(records, out=print):
    """The elastic-capacity timeline (round 13): ``scale`` events — the
    supervisor consensus' shrink/re-expansion decisions (stitched in from
    the ``<stem>.sup.jsonl`` sibling), the engines' coordinated preemption
    snapshots and peer state restores, and serving drains — rendered in
    wall order so a shrink -> degraded attempts -> re-expansion cycle
    reads as one story beside the goodput/restarts sections."""
    scales = sorted((r for r in records if r["event"] == "scale"),
                    key=lambda r: r.get("ts") or 0.0)
    if not scales:
        return None
    # wall anchor: the earliest timestamp anywhere (the supervisor's
    # sibling records are APPENDED to the stream, not ts-interleaved —
    # interleaving would split pseudo-attempts into the goodput math)
    t0 = min((r.get("ts") for r in records if r.get("ts") is not None),
             default=0)
    out(f"\nelasticity ({len(scales)} scale event(s)):")
    rows = []
    for r in scales:
        dt = (r.get("ts") or t0) - t0
        action = str(r.get("action") or "?")
        extras = []
        if r.get("world_from") is not None:
            extras.append(f"{r['world_from']} -> {r.get('processes')} "
                          "process(es)")
        elif r.get("processes") is not None:
            extras.append(f"{r['processes']} process(es)")
        if r.get("hosts") is not None:
            extras.append(f"hosts {r['hosts']}")
        if r.get("step") is not None:
            extras.append(f"step {r['step']}")
        if r.get("shed") is not None:
            extras.append(f"{r['shed']} request(s) shed")
        out(f"  +{dt:8.1f}s  {_SCALE_LABELS.get(action, action):<22}"
            + (f" epoch {r['epoch']}" if r.get("epoch") is not None else "")
            + ("  (" + ", ".join(extras) + ")" if extras else ""))
        rows.append({k: r.get(k) for k in
                     ("action", "processes", "epoch", "hosts", "step",
                      "world_from", "shed", "ts")})
    return rows


def decisions_section(records, out=print):
    """The autoscaling audit (round 20, obs.autoscale): every
    ``scale_decision`` the capacity monitor emitted (a fleet ledger read
    directly, or any stream carrying them) and every ``applied``
    follow-up the supervisor stamped after re-tuning at the new world
    size — rendered in wall order so decision -> rescale -> new plan hash
    reads as one story. ``None`` when the stream has neither."""
    rows = sorted((r for r in records
                   if r["event"] in ("scale_decision", "applied")),
                  key=lambda r: r.get("ts") or 0.0)
    if not rows:
        return None
    t0 = min((r.get("ts") for r in records if r.get("ts") is not None),
             default=0)
    n_dec = sum(1 for r in rows if r["event"] == "scale_decision")
    out(f"\nautoscale decisions ({n_dec} decision(s), "
        f"{len(rows) - n_dec} applied):")
    summary = []
    for r in rows:
        dt = (r.get("ts") or t0) - t0
        if r["event"] == "scale_decision":
            out(f"  +{dt:8.1f}s  {r.get('decision')}: {r.get('direction')} "
                f"{r.get('hosts_from')} -> {r.get('target_hosts')} host(s) "
                f"— {r.get('signal')}={r.get('value')} vs "
                f"{r.get('threshold')} over {r.get('window_ticks')} tick(s)"
                + (f", bundle {r['bundle']}" if r.get("bundle") else ""))
            summary.append({k: r.get(k) for k in
                            ("decision", "direction", "hosts_from",
                             "target_hosts", "signal", "value", "threshold",
                             "window_ticks", "bundle", "ts")})
        else:
            out(f"  +{dt:8.1f}s  {r.get('decision') or '(organic)'} "
                f"applied: {r.get('action')} -> {r.get('processes')} "
                f"process(es) epoch {r.get('epoch')}, plan hash "
                f"{r.get('plan_hash')}")
            summary.append({k: r.get(k) for k in
                            ("decision", "action", "processes", "epoch",
                             "plan_hash", "ts")})
    return summary


def decode_section(records, out=print):
    """The serving-SLO section: per-request latency percentiles and tok/s
    over the `decode` events (engine.generate / tools/decode_bench), plus
    the continuous-batching view over `request`/`admit`/`kv_cache` events
    (engine.serve): queue-wait and TTFT percentiles, admission rejections,
    and batch occupancy from the pool-pressure snapshots."""
    decodes = [r for r in records if r["event"] == "decode"]
    requests = [r for r in records if r["event"] == "request"]
    admits = [r for r in records if r["event"] == "admit"]
    kv = [r for r in records if r["event"] == "kv_cache"]
    if not decodes and not requests and not admits:
        return None
    d = {}
    if decodes:
        secs = sorted(r["seconds"] for r in decodes
                      if r.get("seconds") is not None)
        toks = sum(r.get("tokens") or 0 for r in decodes)
        total_s = sum(secs)
        p50, p99 = _pctl(secs, 50), _pctl(secs, 99)
        d = {"requests": len(decodes), "tokens": toks,
             "tokens_per_sec": round(toks / total_s, 1) if total_s else None,
             "latency_s": {"p50": p50, "p99": p99}}
        out(f"\ndecode: {d['requests']} request(s), {_si(toks, 'tok')}"
            + (f", {d['tokens_per_sec']:,.0f} tok/s" if total_s else "")
            + (f"; latency p50 {p50 * 1e3:.1f}ms / p99 {p99 * 1e3:.1f}ms"
               if p50 is not None else ""))
    if requests or admits:
        waits = sorted(r["queue_wait_s"] for r in requests
                       if r.get("queue_wait_s") is not None)
        ttfts = sorted(r["ttft_s"] for r in requests
                       if r.get("ttft_s") is not None)
        toks = sum(r.get("tokens") or 0 for r in requests)
        rejected = sum(1 for r in admits if not r.get("accepted"))
        srv = {"completed": len(requests), "tokens": toks,
               "rejected": rejected,
               "queue_wait_s": {"p50": _pctl(waits, 50),
                                "p99": _pctl(waits, 99)},
               "ttft_s": {"p50": _pctl(ttfts, 50), "p99": _pctl(ttfts, 99)}}
        if kv:
            # occupancy from the pool snapshots: active slots over capacity
            occ = [r["active_seqs"] / r["slots"] for r in kv
                   if r.get("active_seqs") is not None and r.get("slots")]
            srv["occupancy"] = round(_mean(occ), 4) if occ else None
            last = kv[-1]
            srv["pages_free_last"] = last.get("pages_free")
            srv["high_water_used"] = last.get("high_water_used")
            # round 16: speculative-acceptance and prefix-hit TRENDS over
            # the periodic snapshots (counters are cumulative, so per-
            # window rates come from consecutive deltas: first -> last)
            srv["spec_acceptance"] = _counter_trend(
                kv, "spec_emitted", "spec_slot_ticks")
            srv["prefix_hits_last"] = last.get("prefix_hits")
            srv["cow_copies_last"] = last.get("cow_copies")
            srv["shared_pages_last"] = last.get("shared_pages")
            # round 19: the long-context serving plane — chunk-prefill
            # occupancy (share of scheduler steps that ran a prefill
            # chunk, from the cumulative chunk_ticks/tick counters) and
            # the chunk-queue depth gauge (pending chunks across parked
            # slots: max = worst backlog, last = drained or not)
            co = _counter_trend(kv, "chunk_ticks", "tick")
            # tick always advances, so the trend is 0.0 (not None) on a
            # run that never chunked — treat that as absent
            srv["chunk_occupancy"] = co if co and co["overall"] else None
            depths = [r["chunks_pending"] for r in kv
                      if r.get("chunks_pending") is not None]
            srv["chunks_pending_max"] = max(depths) if depths else None
            srv["chunks_pending_last"] = depths[-1] if depths else None
            srv["sharded_devices"] = last.get("sharded_devices")
        d["serving"] = srv
        out(f"\nserving: {srv['completed']} completed, {rejected} rejected"
            + (f", occupancy {srv['occupancy'] * 100:.0f}%"
               if srv.get("occupancy") is not None else "")
            + (f"; queue wait p50 {srv['queue_wait_s']['p50'] * 1e3:.1f}ms"
               f" / p99 {srv['queue_wait_s']['p99'] * 1e3:.1f}ms"
               if waits else "")
            + (f"; TTFT p50 {srv['ttft_s']['p50'] * 1e3:.1f}ms"
               f" / p99 {srv['ttft_s']['p99'] * 1e3:.1f}ms"
               if ttfts else ""))
        sa = srv.get("spec_acceptance")
        if sa is not None:
            out("  speculative acceptance: "
                + f"{sa['overall']:.2f} tokens/slot-tick overall"
                + (f" (first window {sa['first']:.2f} -> last "
                   f"{sa['last']:.2f})"
                   if sa.get("first") is not None else ""))
        if srv.get("prefix_hits_last"):
            out(f"  prefix cache: {srv['prefix_hits_last']} page hits, "
                f"{srv['cow_copies_last'] or 0} CoW forks, "
                f"{srv['shared_pages_last'] or 0} pages shared at last "
                "snapshot")
        co = srv.get("chunk_occupancy")
        if co is not None:
            out("  chunked prefill: "
                + f"{co['overall'] * 100:.0f}% of steps ran a chunk"
                + (f" (first window {co['first'] * 100:.0f}% -> last "
                   f"{co['last'] * 100:.0f}%)"
                   if co.get("first") is not None else "")
                + (f"; queue depth max {srv['chunks_pending_max']}, "
                   f"last {srv['chunks_pending_last']}"
                   if srv.get("chunks_pending_max") is not None else ""))
        if (srv.get("sharded_devices") or 0) > 1:
            out(f"  sp-sharded KV pool: {srv['sharded_devices']} devices")
    return d


def _counter_trend(kv, num_key, den_key):
    """Overall + first/last per-window rate of two CUMULATIVE counters
    across the periodic ``kv_cache`` snapshots (None when the counters
    never moved — plain non-speculative serving)."""
    pts = [(r.get(num_key), r.get(den_key)) for r in kv
           if r.get(num_key) is not None and r.get(den_key) is not None]
    if not pts or not pts[-1][1]:
        return None
    trend = {"overall": round(pts[-1][0] / pts[-1][1], 4),
             "first": None, "last": None}
    deltas = []
    prev = (0, 0)
    for num, den in pts:
        dn, dd = num - prev[0], den - prev[1]
        if dd > 0:
            deltas.append(dn / dd)
        prev = (num, den)
    if deltas:
        trend["first"] = round(deltas[0], 4)
        trend["last"] = round(deltas[-1], 4)
    return trend


def audit_section(records, out=print):
    """Program-audit rollup (``audit`` events — analysis.proglint via
    plan.compile): per-program unwaivered/waived finding counts and the
    check ids involved. None when the run predates the audit knob or
    ran with audit=none."""
    audits = [r for r in records if r["event"] == "audit"]
    if not audits:
        return None
    progs = {}
    for r in audits:
        p = progs.setdefault(r.get("program") or "?",
                             {"events": 0, "findings": 0, "waived": 0,
                              "checks": []})
        p["events"] += 1
        p["findings"] += r.get("findings") or 0
        p["waived"] += r.get("waived") or 0
        for d in (r.get("detail") or ()):
            c = d.get("check")
            if c and c not in p["checks"]:
                p["checks"].append(c)
    for p in progs.values():
        p["checks"].sort()
    total = sum(p["findings"] for p in progs.values())
    waived = sum(p["waived"] for p in progs.values())
    mode = audits[-1].get("mode") or "record"
    out(f"\naudit ({mode}): {len(progs)} program(s), {total} unwaivered "
        f"finding(s), {waived} waived")
    for name in sorted(progs):
        p = progs[name]
        if p["findings"] or p["waived"]:
            out(f"  {name}: {p['findings']} finding(s)"
                + (f" + {p['waived']} waived" if p["waived"] else "")
                + (f" [{', '.join(p['checks'])}]" if p["checks"] else ""))
    return {"mode": mode, "programs": {n: progs[n] for n in sorted(progs)},
            "findings": total, "waived": waived}


def requests_section(records, out=print):
    """Per-request tracing rollup (obs.reqtrace ``span`` events): the
    waterfall summary, the tail-latency attribution table with its
    per-request sum-check, and the SLO-breach exemplar pointers — all
    delegated to tools/request_report (the span model's reading side) so
    this CLI and that one render the same math. None when the ledger
    predates spans (pre-PR-17 history stays renderable)."""
    if not any(r.get("event") == "span" for r in records):
        return None
    from tools.request_report import render as render_requests
    from tools.request_report import requests_summary

    summary = requests_summary(records)
    out("")
    render_requests(summary, records, out=out, waterfalls=1)
    return summary


def summarize(records, out=print):
    """Render the summary through ``out`` and return the machine-readable
    dict (--json prints it verbatim; the legacy count keys ride along)."""
    runs = [r for r in records if r["event"] == "run_start"]
    steps = [r for r in records if r["event"] == "step"]
    epochs = [r for r in records if r["event"] == "epoch"]
    evals = [r for r in records if r["event"] == "eval"]
    skews = [r for r in records if r["event"] == "skew"
             and r.get("spread_s") is not None]
    stalls = [r for r in records if r["event"] == "stall"]
    healths = [r for r in records if r["event"] == "health"]
    diags = [r for r in records if r["event"] == "diagnosis"]
    cost_models = [r for r in records if r["event"] == "cost_model"]
    ends = [r for r in records if r["event"] == "run_end"]
    summary = {"steps": len(steps), "epochs": len(epochs),
               "skews": len(skews), "stalls": len(stalls),
               "health": len(healths), "diagnosis": len(diags)}

    for r in runs:
        out(f"run: kind={r['kind']} devices={r.get('devices')} "
            f"mesh={r.get('mesh')} processes={r.get('process_count')}"
            + (" (MFU vs NOMINAL peak)" if r.get("peak_is_nominal") else ""))
        summary["run"] = {k: r.get(k) for k in
                          ("kind", "devices", "mesh", "process_count",
                           "peak_tflops", "peak_is_nominal", "jax_version",
                           "plan_hash", "plan_source", "plan_knobs")}
    # resolved step plan (tpu_dist.plan): which tuned/loaded plan drove the
    # step compilation — the tuner's measured-refinement loop reads this
    # back (tools/tune.py --ledger-summary keys trials on run.plan_hash)
    plans = [r for r in records if r["event"] == "plan"]
    for r in plans[-1:]:
        out(f"plan: {r.get('plan_hash')} from {r.get('source')}"
            + (f" (device {r['device_kind']})" if r.get("device_kind")
               else "")
            + (f"\n  knobs: {r.get('knobs')}" if r.get("knobs") else ""))
        summary["plan"] = {k: r.get(k) for k in
                           ("source", "plan_hash", "knobs", "device_kind")}
    # auto-tuner invocations appended to this ledger (tools/tune.py)
    tunes = [r for r in records if r["event"] == "tune"]
    if tunes:
        for r in tunes:
            out(f"tune: {r.get('device_kind')}: best {r.get('best_hash')} "
                f"over {r.get('candidates')} candidate(s)"
                + (" [measured]" if r.get("measured") else " [analytic]"))
        summary["tune"] = [{k: r.get(k) for k in
                            ("device_kind", "candidates", "best_hash",
                             "best_step_s", "measured")} for r in tunes]
    if ends:
        secs = ends[-1]["seconds"]
        status = ends[-1].get("status") or "ok"
        summary["run_end"] = {"status": status, "steps": ends[-1]["steps"],
                              "seconds": secs}
        out(f"{'CRASHED' if status == 'crashed' else 'PREEMPTED (snapshotted)' if status == 'preempted' else 'completed'}: "
            f"{ends[-1]['steps']} steps in "
            + (f"{secs:.1f}s" if secs is not None else "?s")
            + "".join(f" {k}={v}" for k, v in ends[-1].items()
                      if k not in ("event", "ts", "pid", "steps", "seconds",
                                   "error", "metrics"))
            + (f"\n  error: {ends[-1]['error'].strip().splitlines()[-1]}"
               if ends[-1].get("error") else ""))
    elif records:
        out("NO run_end record: the writer died mid-run (crash/SIGKILL) — "
            "the events below are everything that reached disk")

    # wall-clock accounting (obs.goodput) — attempts stitched, gaps charged
    summary["goodput"] = goodput_section(records, out=out)
    # remediation view (parallel.supervisor lineage): per-attempt failure
    # classes, injected-vs-organic faults, crash-loop banner
    summary["restarts"] = restarts_section(records, out=out)
    # elastic-capacity timeline (round 13): shrink -> degraded attempts ->
    # re-expansion, preemption snapshots, peer restores, serve drains
    summary["elasticity"] = elasticity_section(records, out=out)
    # autoscaling audit (round 20): scale_decision + applied follow-ups
    summary["autoscale"] = decisions_section(records, out=out)

    if steps:
        # warm records carry the XLA compile in dispatch_s; exclude them
        # from shares/trends (the loops' own warm-excluded tok/s
        # convention) — the compile cost lives in the 'compile' event
        warm_n = sum(1 for r in steps if r.get("warm"))
        hot = [r for r in steps if not r.get("warm")] or steps
        tot = phase_totals(hot)
        # comm_s OVERLAPS device_s (obs.ledger schema note): it reports
        # beside the share table, never inside its denominator
        total = tot["data_s"] + tot["dispatch_s"] + tot["device_s"] or 1.0
        summary["phase_totals"] = tot
        out(f"\nsteps: {sum(r.get('steps_in_dispatch') or 1 for r in steps)} "
            f"optimizer steps in {len(steps)} records"
            + (f" ({warm_n} warm/compile record(s) excluded from shares)"
               if warm_n and hot is not steps else ""))
        out("phase time share (host-measured):")
        for k, label in (("data_s", "data wait"), ("dispatch_s", "dispatch"),
                         ("device_s", "device block")):
            out(f"  {label:<13} {tot[k]:9.3f}s  {tot[k] / total * 100:5.1f}%")
        if tot.get("comm_s"):
            dev = tot["device_s"] or 1e-9
            out(f"  comm          {tot['comm_s']:9.3f}s  "
                f"{tot['comm_s'] / dev * 100:5.1f}% of the device block "
                "(unoverlapped-cost estimate; overlap shows as device_s "
                "growing LESS than comm_s when buckets/rings land)")
        tp = [r["throughput"] for r in hot if r["throughput"] is not None]
        mfu = [r["mfu"] for r in hot if r["mfu"] is not None]
        summary["roofline"] = roofline(cost_models, hot,
                                       mfu_mean=_mean(mfu), out=out)
        a, b, c = _thirds(tp)
        if a is not None:
            out(f"throughput ({hot[0]['unit']}): first/mid/last thirds "
                f"{a:,.0f} / {b:,.0f} / {c:,.0f}")
            summary["throughput"] = {"unit": hot[0]["unit"], "thirds":
                                     [a, b, c], "mean": _mean(tp)}
        a, b, c = _thirds(mfu)
        if a is not None:
            out(f"MFU trend: {_fmt_mfu(a)} -> {_fmt_mfu(b)} -> {_fmt_mfu(c)}"
                f"  (mean {_fmt_mfu(_mean(mfu))})")
            summary["mfu"] = {"thirds": [a, b, c], "mean": _mean(mfu)}
        ds = [r["data_s"] for r in hot if r.get("data_s") is not None]
        a, b, c = _thirds(ds)
        if a is not None:
            out(f"data wait trend: {a:.4f}s -> {b:.4f}s -> {c:.4f}s per "
                f"record  (mean {_mean(ds):.4f}s; ~0 means the prefetcher "
                "hid the host->device copies)")
            summary["data_s"] = {"thirds": [a, b, c], "mean": _mean(ds)}
        # fused-kernel attribution: records carrying the boolean `fused`
        # extra (engines + bench since round 9) split on it, so an MFU
        # delta is attributable to the fused int8 Pallas kernel from the
        # ledger alone — no side-channel config needed
        flagged = [r for r in hot if r.get("fused") is not None]
        if flagged:
            groups = {}
            for r in flagged:
                groups.setdefault(bool(r["fused"]), []).append(r)
            split = {}
            for flag, rs in sorted(groups.items()):
                split["fused" if flag else "unfused"] = {
                    "records": len(rs),
                    "throughput_mean": _mean(
                        r["throughput"] for r in rs
                        if r.get("throughput") is not None),
                    "mfu_mean": _mean(r["mfu"] for r in rs
                                      if r.get("mfu") is not None)}
            summary["fused_split"] = split
            if len(split) == 2:
                mf, mu = (split["fused"]["mfu_mean"],
                          split["unfused"]["mfu_mean"])
                out("fused int8 kernel: "
                    f"{split['fused']['records']} fused record(s) at MFU "
                    f"{_fmt_mfu(mf)} vs {split['unfused']['records']} "
                    f"unfused at {_fmt_mfu(mu)}"
                    + (f" -> delta {_fmt_mfu(mf - mu)}"
                       if mf is not None and mu is not None else ""))
            else:
                only = next(iter(split))
                s = split[only]
                out(f"fused int8 kernel: all {s['records']} flagged "
                    f"record(s) {only}"
                    + (f" (MFU mean {_fmt_mfu(s['mfu_mean'])})"
                       if s["mfu_mean"] is not None else ""))

    if epochs:
        out("\nepochs:")
        summary["epoch_table"] = []
        for r in epochs:
            # schema-legal None values render as '?' (presence, not
            # non-nullness, is what the schema pins)
            out(f"  [{r['epoch']}] loss=" + _num(r["loss"], ".4f")
                + f" {_num(r['throughput'], ',.0f')} {r['unit']} "
                f"({_num(r['seconds'], '.1f')}s)"
                + (f" ppl={r['ppl']:.2f}" if r.get("ppl") else "")
                + (f" acc1={r['acc1'] * 100:.2f}%" if r.get("acc1") is not None
                   else ""))
            summary["epoch_table"].append(
                {k: r.get(k) for k in ("epoch", "loss", "throughput", "unit",
                                       "seconds", "ppl", "acc1")})
    if evals:
        last = evals[-1]
        out("last eval: loss=" + _num(last["loss"], ".4f")
            + (f" ppl={last['ppl']:.2f}" if last.get("ppl") else "")
            + (f" acc1={last['acc1'] * 100:.2f}%"
               if last.get("acc1") is not None else ""))
        summary["last_eval"] = {k: last.get(k)
                                for k in ("epoch", "loss", "ppl", "acc1")}

    # serving-SLO view over decode events (generate / decode_bench)
    summary["decode"] = decode_section(records, out=out)
    summary["requests"] = requests_section(records, out=out)
    # program-audit verdicts (analysis.proglint): which step/serve
    # programs were audited and what survived the waiver file
    summary["audit"] = audit_section(records, out=out)

    if skews:
        worst = max(skews, key=lambda r: r["spread_s"])
        hist = {}
        for r in skews:
            hist[r["straggler"]] = hist.get(r["straggler"], 0) + 1
        out(f"\nskew: {len(skews)} samples; worst spread "
            f"{worst['spread_s'] * 1e3:.1f}ms at step {worst['step']} "
            f"(straggler process {worst['straggler']}); "
            f"p50 {worst['p50_s'] * 1e3:.1f}ms p99 {worst['p99_s'] * 1e3:.1f}ms")
        out(f"straggler histogram (process: samples): {hist}")
        summary["skew"] = {"worst_spread_s": worst["spread_s"],
                           "straggler_histogram":
                           {str(k): v for k, v in hist.items()}}

    if healths:
        kinds = {}
        for r in healths:
            kinds[r.get("kind")] = kinds.get(r.get("kind"), 0) + 1
        out(f"\nHEALTH TRIPS: {len(healths)} "
            f"({', '.join(f'{k}: {n}' for k, n in sorted(kinds.items()))}; "
            f"policy {healths[-1].get('policy')})")
        for r in healths[-5:]:
            out(f"  step {r.get('step')}: {r.get('kind')} "
                f"value={r.get('value')} loss={r.get('loss')} "
                f"-> {r.get('action')}")
        summary["health_kinds"] = kinds

    if diags:
        out(f"\nDIAGNOSIS BUNDLES: {len(diags)} (obs.flightrec)")
        summary["diagnosis_bundles"] = []
        for r in diags:
            out(f"  [{r.get('reason')}] step {r.get('step')} -> "
                f"{r.get('bundle')} (trace: {r.get('trace')})"
                + (f" — {r['note']}" if r.get("note") else ""))
            summary["diagnosis_bundles"].append(
                {k: r.get(k) for k in ("reason", "step", "bundle", "trace",
                                       "note")})

    if stalls:
        out(f"\nWATCHDOG STALLS: {len(stalls)}")
        for r in stalls:
            out(f"  idle {_num(r['idle_s'], '.1f')}s (threshold "
                f"{_num(r['threshold_s'], '.1f')}s) — first stack lines:")
            for line in (r.get("stacks") or "").splitlines()[:6]:
                out(f"    {line}")
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="ledger JSONL (obs.ledger)")
    ap.add_argument("--tail", type=int, default=0,
                    help="also render the last N step records as lines")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON object on stdout "
                    "(human render suppressed)")
    ap.add_argument("--no-discover", action="store_true",
                    help="read only the given file (no .aN restart-attempt "
                    "sibling stitching)")
    args = ap.parse_args(argv)
    # restart lineage (obs.goodput): stitch every attempt of the job —
    # plus the supervisor's .sup.jsonl scale-event sibling, APPENDED,
    # never ts-interleaved — so the goodput section sees crash->restart
    # gaps. load_job_records is THE job-loading rule (the fleet stitcher
    # tpu_dist.sim.fleet runs it once per host); torn trailing lines and
    # unreadable files warn instead of raising, because a crashed run is
    # exactly the one being inspected.
    from tpu_dist.obs.goodput import discover_attempt_paths, load_job_records

    if not args.no_discover and not args.json:
        paths = discover_attempt_paths(args.path) or [args.path]
        if len(paths) > 1:
            print(f"stitching {len(paths)} attempt ledgers: "
                  f"{[os.path.basename(p) for p in paths]}")
    records = load_job_records(args.path, discover=not args.no_discover)
    if not records:
        print(f"{args.path}: empty ledger", file=sys.stderr)
        return 1
    if args.json:
        summary = summarize(records, out=lambda s: None)
        print(json.dumps(summary, default=str))
        return 0
    summarize(records)
    if args.tail:
        print(f"\nlast {args.tail} step records:")
        sink = ProgressSink()
        for r in [r for r in records if r["event"] == "step"][-args.tail:]:
            sink(r)
    import glob

    root, ext = os.path.splitext(args.path)
    if glob.glob(f"{glob.escape(root)}.p*{ext}"):
        print(f"\nper-process sibling ledgers found — merge the lanes into "
              f"one Chrome trace with: python tools/trace_merge.py "
              f"{args.path}")
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # `ledger_report run.jsonl | head` closing the pipe is normal use
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        raise SystemExit(0)
