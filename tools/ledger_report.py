#!/usr/bin/env python
"""Summarize a tpu_dist run ledger (obs.ledger JSONL) from the CLI.

    python tools/ledger_report.py run.jsonl            # summary
    python tools/ledger_report.py run.jsonl --tail 20  # + last N step lines

Renders: run identity (kind/mesh/devices/processes), per-phase time share
(data wait vs dispatch vs device block across every step record), MFU and
throughput trend (first/middle/last thirds), the epoch table, cross-host
skew/straggler summary, numerical-health trips (obs.health), and any
watchdog stall dumps; multi-process runs get a pointer at the merged
Chrome trace (tools/trace_merge.py). Corrupt/truncated trailing lines —
crashed runs are exactly the ones inspected here — are skipped with a
warning, never a crash. Pure stdlib + the ledger module — safe to run on
a login host with no jax installed (obs.ledger imports nothing heavy).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_dist.obs.ledger import ProgressSink, phase_totals, read_ledger  # noqa: E402


def _mean(xs):
    xs = list(xs)
    return sum(xs) / len(xs) if xs else None


def _fmt_mfu(x):
    return f"{x * 100:.1f}%" if x is not None else "n/a"


def _num(v, spec):
    """None-tolerant numeric cell ('?' for a schema-legal null)."""
    return f"{v:{spec}}" if v is not None else "?"


def _thirds(xs):
    """(first, middle, last) third means — the cheap trend view."""
    if not xs:
        return None, None, None
    n = max(len(xs) // 3, 1)
    return _mean(xs[:n]), _mean(xs[len(xs) // 2 - n // 2:
                                   len(xs) // 2 - n // 2 + n]), _mean(xs[-n:])


def summarize(records, out=print):
    runs = [r for r in records if r["event"] == "run_start"]
    steps = [r for r in records if r["event"] == "step"]
    epochs = [r for r in records if r["event"] == "epoch"]
    evals = [r for r in records if r["event"] == "eval"]
    skews = [r for r in records if r["event"] == "skew"
             and r.get("spread_s") is not None]
    stalls = [r for r in records if r["event"] == "stall"]
    healths = [r for r in records if r["event"] == "health"]
    ends = [r for r in records if r["event"] == "run_end"]

    for r in runs:
        out(f"run: kind={r['kind']} devices={r.get('devices')} "
            f"mesh={r.get('mesh')} processes={r.get('process_count')}"
            + (" (MFU vs NOMINAL peak)" if r.get("peak_is_nominal") else ""))
    if ends:
        secs = ends[-1]["seconds"]
        status = ends[-1].get("status") or "ok"
        out(f"{'CRASHED' if status == 'crashed' else 'completed'}: "
            f"{ends[-1]['steps']} steps in "
            + (f"{secs:.1f}s" if secs is not None else "?s")
            + "".join(f" {k}={v}" for k, v in ends[-1].items()
                      if k not in ("event", "ts", "pid", "steps", "seconds",
                                   "error", "metrics"))
            + (f"\n  error: {ends[-1]['error'].strip().splitlines()[-1]}"
               if ends[-1].get("error") else ""))
    elif records:
        out("NO run_end record: the writer died mid-run (crash/SIGKILL) — "
            "the events below are everything that reached disk")

    if steps:
        # warm records carry the XLA compile in dispatch_s; exclude them
        # from shares/trends (the loops' own warm-excluded tok/s
        # convention) — the compile cost lives in the 'compile' event
        warm_n = sum(1 for r in steps if r.get("warm"))
        hot = [r for r in steps if not r.get("warm")] or steps
        tot = phase_totals(hot)
        # comm_s OVERLAPS device_s (obs.ledger schema note): it reports
        # beside the share table, never inside its denominator
        total = tot["data_s"] + tot["dispatch_s"] + tot["device_s"] or 1.0
        out(f"\nsteps: {sum(r.get('steps_in_dispatch') or 1 for r in steps)} "
            f"optimizer steps in {len(steps)} records"
            + (f" ({warm_n} warm/compile record(s) excluded from shares)"
               if warm_n and hot is not steps else ""))
        out("phase time share (host-measured):")
        for k, label in (("data_s", "data wait"), ("dispatch_s", "dispatch"),
                         ("device_s", "device block")):
            out(f"  {label:<13} {tot[k]:9.3f}s  {tot[k] / total * 100:5.1f}%")
        if tot.get("comm_s"):
            dev = tot["device_s"] or 1e-9
            out(f"  comm          {tot['comm_s']:9.3f}s  "
                f"{tot['comm_s'] / dev * 100:5.1f}% of the device block "
                "(unoverlapped-cost estimate; overlap shows as device_s "
                "growing LESS than comm_s when buckets/rings land)")
        tp = [r["throughput"] for r in hot if r["throughput"] is not None]
        mfu = [r["mfu"] for r in hot if r["mfu"] is not None]
        a, b, c = _thirds(tp)
        if a is not None:
            out(f"throughput ({hot[0]['unit']}): first/mid/last thirds "
                f"{a:,.0f} / {b:,.0f} / {c:,.0f}")
        a, b, c = _thirds(mfu)
        if a is not None:
            out(f"MFU trend: {_fmt_mfu(a)} -> {_fmt_mfu(b)} -> {_fmt_mfu(c)}"
                f"  (mean {_fmt_mfu(_mean(mfu))})")

    if epochs:
        out("\nepochs:")
        for r in epochs:
            # schema-legal None values render as '?' (presence, not
            # non-nullness, is what the schema pins)
            out(f"  [{r['epoch']}] loss=" + _num(r["loss"], ".4f")
                + f" {_num(r['throughput'], ',.0f')} {r['unit']} "
                f"({_num(r['seconds'], '.1f')}s)"
                + (f" ppl={r['ppl']:.2f}" if r.get("ppl") else "")
                + (f" acc1={r['acc1'] * 100:.2f}%" if r.get("acc1") is not None
                   else ""))
    if evals:
        last = evals[-1]
        out("last eval: loss=" + _num(last["loss"], ".4f")
            + (f" ppl={last['ppl']:.2f}" if last.get("ppl") else "")
            + (f" acc1={last['acc1'] * 100:.2f}%"
               if last.get("acc1") is not None else ""))

    if skews:
        worst = max(skews, key=lambda r: r["spread_s"])
        hist = {}
        for r in skews:
            hist[r["straggler"]] = hist.get(r["straggler"], 0) + 1
        out(f"\nskew: {len(skews)} samples; worst spread "
            f"{worst['spread_s'] * 1e3:.1f}ms at step {worst['step']} "
            f"(straggler process {worst['straggler']}); "
            f"p50 {worst['p50_s'] * 1e3:.1f}ms p99 {worst['p99_s'] * 1e3:.1f}ms")
        out(f"straggler histogram (process: samples): {hist}")

    if healths:
        kinds = {}
        for r in healths:
            kinds[r.get("kind")] = kinds.get(r.get("kind"), 0) + 1
        out(f"\nHEALTH TRIPS: {len(healths)} "
            f"({', '.join(f'{k}: {n}' for k, n in sorted(kinds.items()))}; "
            f"policy {healths[-1].get('policy')})")
        for r in healths[-5:]:
            out(f"  step {r.get('step')}: {r.get('kind')} "
                f"value={r.get('value')} loss={r.get('loss')} "
                f"-> {r.get('action')}")

    if stalls:
        out(f"\nWATCHDOG STALLS: {len(stalls)}")
        for r in stalls:
            out(f"  idle {_num(r['idle_s'], '.1f')}s (threshold "
                f"{_num(r['threshold_s'], '.1f')}s) — first stack lines:")
            for line in (r.get("stacks") or "").splitlines()[:6]:
                out(f"    {line}")
    return {"steps": len(steps), "epochs": len(epochs), "skews": len(skews),
            "stalls": len(stalls), "health": len(healths)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="ledger JSONL (obs.ledger)")
    ap.add_argument("--tail", type=int, default=0,
                    help="also render the last N step records as lines")
    args = ap.parse_args(argv)
    # strict=False: a crashed writer leaves a torn trailing line, and a
    # crashed run is exactly the one being inspected — warn, don't raise
    records = read_ledger(args.path, strict=False)
    if not records:
        print(f"{args.path}: empty ledger", file=sys.stderr)
        return 1
    summarize(records)
    if args.tail:
        print(f"\nlast {args.tail} step records:")
        sink = ProgressSink()
        for r in [r for r in records if r["event"] == "step"][-args.tail:]:
            sink(r)
    import glob

    root, ext = os.path.splitext(args.path)
    if glob.glob(f"{glob.escape(root)}.p*{ext}"):
        print(f"\nper-process sibling ledgers found — merge the lanes into "
              f"one Chrome trace with: python tools/trace_merge.py "
              f"{args.path}")
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # `ledger_report run.jsonl | head` closing the pipe is normal use
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        raise SystemExit(0)
