#!/usr/bin/env python
"""Steps/seconds to a val top-1 threshold (the convergence north star).

The reference's only QA signal was convergence watched by hand
(reference README_EN.md:10 "Tested..."); BASELINE.json's north star is
time-to-90% top-1. This tool measures it on the learnable synthetic CIFAR
set (fixed seed, deterministic sampler): it trains epoch by epoch with the
SAME Trainer the cookbook scripts use and reports the first optimizer step
count (and wall seconds) at which distributed eval reaches --threshold.

Per-variant numbers (jit / shard_map / bf16) are recorded in BASELINE.md;
tests/test_convergence.py holds the fast regression bound.

Usage (single chip or any mesh):
    python tools/convergence.py --variant jit --precision bf16
    python tools/convergence.py --variant shard_map --precision fp32
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--arch", default="resnet50")
    ap.add_argument("--dataset", default="synthetic")
    ap.add_argument("--variant", default="jit", choices=["jit", "shard_map"])
    ap.add_argument("--precision", default="bf16",
                    choices=["fp32", "bf16", "bf16_params"])
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--synth-train-size", type=int, default=10240)
    ap.add_argument("--synth-val-size", type=int, default=2048)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--threshold", type=float, default=0.90)
    ap.add_argument("--max-epochs", type=int, default=20)
    ap.add_argument("--steps-per-dispatch", type=int, default=1)
    ap.add_argument("--norm-dtype", default="",
                    help="'' (fp32 norm outputs) | bf16 (MLPerf-TPU "
                         "practice) — accuracy-parity check for the bench's "
                         "norm_dtype lever")
    ap.add_argument("--stem", default="",
                    help="imagenet | cifar | s2d (space-to-depth)")
    args = ap.parse_args()

    import jax

    from tpu_dist.configs import TrainConfig
    from tpu_dist.engine import Trainer

    cfg = TrainConfig(
        arch=args.arch, dataset=args.dataset, variant=args.variant,
        precision=args.precision, batch_size=args.batch_size,
        synth_train_size=args.synth_train_size,
        synth_val_size=args.synth_val_size, lr=args.lr, seed=args.seed,
        epochs=args.max_epochs, print_freq=10 ** 9,
        steps_per_dispatch=args.steps_per_dispatch,
        norm_dtype=args.norm_dtype, stem=args.stem,
        checkpoint_dir=os.path.join("/tmp", "convergence_ck"))
    tr = Trainer(cfg)

    # warm up compilation OUTSIDE the timed region (one throwaway epoch on a
    # cloned trainer would cost accuracy; instead time from t0 but report
    # epoch-0 wall separately so compile time is visible)
    t0 = time.time()
    result = None
    for epoch in range(cfg.epochs):
        tr.train_epoch(epoch)
        # distlint: disable=DL002 -- epoch boundary: train_epoch just drained the device queue
        steps = int(jax.device_get(tr.state.step))
        acc = tr.validate(epoch)
        if jax.process_index() == 0:
            print(f"epoch {epoch}: step {steps} val_top1 {acc * 100:.2f}%",
                  file=sys.stderr, flush=True)
        if acc >= args.threshold:
            result = {"steps_to_threshold": steps,
                      "seconds_to_threshold": round(time.time() - t0, 2),
                      # distlint: disable=DL002 -- validate() returns an already-drained host scalar
                      "epochs": epoch + 1, "val_top1": round(float(acc), 4)}
            break
    if jax.process_index() == 0:
        out = {"metric": f"steps_to_{int(args.threshold * 100)}pct_top1",
               "variant": args.variant, "precision": args.precision,
               "arch": args.arch, "batch_size": args.batch_size,
               "train_size": args.synth_train_size, "seed": args.seed,
               "norm_dtype": args.norm_dtype or "fp32", "stem": args.stem,
               **(result or {"steps_to_threshold": None,
                             "note": f"not reached in {cfg.epochs} epochs"})}
        print(json.dumps(out))


if __name__ == "__main__":
    main()
