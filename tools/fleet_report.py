#!/usr/bin/env python
"""Render a stitched fleet (tpu_dist.sim.fleet.FleetLedger) from the CLI.

    python tools/fleet_report.py /tmp/fleet           # fleet summary
    python tools/fleet_report.py /tmp/fleet --json    # machine-readable

``PATH`` is a fleet directory (the tpu_dist.sim.runner layout:
``host<N>/run.jsonl`` families + the runner's ``fleet.jsonl``); any tree
of per-host supervised runs with that shape works — the simulator is one
producer, not the only one. Renders: the scenario identity, the fleet
goodput partition (per-host goodput/badput aggregated over every attempt
and restart gap, with the sum-check that proves categories + goodput
account for ~100% of the aggregate wall), the restart-class histogram
and per-host class lists (`classify_attempt` in report mode), the
fleet-wide SLO-breach count, the cross-host elasticity timeline (every
``scale`` event on the fleet clock), per-tenant request percentiles,
the hosts-live timeline from the runner's periodic ``fleet`` events, and
— when the run autoscaled — the decision audit (every ``scale_decision``
with its attribution, the paired scale event's lag, and the retuned plan
hash from the ``applied`` follow-up).

``--json`` prints :meth:`FleetLedger.report` verbatim — the stable input
the CI acceptance (tests/test_fleet.py) asserts into. Per-host detail
beyond this summary is one ``tools/ledger_report.py host<N>/run.jsonl``
away (same records, same loader). Stdlib + the jax-free sim/obs modules —
safe on a login host.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_dist.sim.fleet import FleetLedger  # noqa: E402

GOODPUT_LABELS = {"startup": "startup/compile", "data_wait": "data wait",
                  "dispatch": "dispatch", "eval": "eval",
                  "ckpt": "checkpoint", "stall": "watchdog stall",
                  "skipped": "health-skipped", "idle": "idle/drain",
                  "restart_gap": "restart gap"}


def render(report: dict, out=print) -> None:
    sc = report.get("scenario")
    if sc:
        out(f"scenario: {sc.get('name')!r} seed={sc.get('seed')} "
            f"hosts={sc.get('hosts')} ticks={sc.get('ticks')} "
            f"tick_s={sc.get('tick_s')}")
    hosts = report.get("hosts") or []
    out(f"fleet: {len(hosts)} host dir(s) discovered")
    acct = report.get("fleet")
    if acct and not acct["aggregate_wall_s"]:
        # every host died at (or before) its first timestamp — the wall
        # is zero and there are no shares to print; this report exists
        # for exactly such fleets, so say it instead of dividing by it
        out(f"\nfleet goodput: {acct['hosts']} host(s) but ZERO aggregate "
            "wall (no host survived past its first record)")
    elif acct:
        wall = acct["aggregate_wall_s"]
        out(f"\nfleet goodput ({acct['hosts']} host(s), aggregate wall "
            f"{wall:.1f} host-seconds):")
        rows = [("goodput", acct["goodput_s"])] + [
            (c, acct["categories"].get(c, 0.0)) for c in GOODPUT_LABELS]
        for cat, secs in rows:
            if cat != "goodput" and not secs:
                continue
            out(f"  {GOODPUT_LABELS.get(cat, cat):<16} {secs:9.3f}s  "
                f"{secs / wall * 100:5.1f}%")
        out(f"  fleet goodput ratio {acct['goodput_ratio']:.3f} over "
            f"{acct['opt_steps']} tick(s); categories + goodput account "
            f"for {acct['sum_check'] * 100:.1f}% of aggregate wall"
            + (f"; OVERRUN {acct['overrun_s']:.3f}s"
               if acct.get("overrun_s") else ""))
        for h, hj in sorted(acct.get("per_host", {}).items()):
            out(f"  host {h}: {hj['wall_s']:.1f}s wall, "
                f"{hj['goodput_s']:.1f}s goodput "
                f"(ratio {hj['ratio']}), {hj['attempts']} attempt(s)")
    hist = report.get("restart_histogram") or {}
    classes = report.get("restart_classes") or {}
    if hist:
        out(f"\nrestarts: histogram {hist}")
        for h, cls in sorted(classes.items(), key=lambda kv: int(kv[0])):
            out(f"  host {h}: {' -> '.join(cls) if cls else '(no attempts)'}")
    out(f"\nSLO breaches (fleet-wide): {report.get('slo_breaches')}")
    tenants = report.get("per_tenant") or {}
    if tenants:
        out("\nper-tenant serving:")
        for name, t in tenants.items():
            qw, tt = t["queue_wait_s"], t["ttft_s"]
            out(f"  {name:<12} {t['requests']:4d} request(s), "
                f"{t['tokens']} tok"
                + (f"; queue wait p50 {qw['p50'] * 1e3:.1f}ms / "
                   f"p99 {qw['p99'] * 1e3:.1f}ms"
                   if qw["p50"] is not None else "")
                + (f"; TTFT p50 {tt['p50'] * 1e3:.1f}ms / "
                   f"p99 {tt['p99'] * 1e3:.1f}ms"
                   if tt["p50"] is not None else ""))
    srv = report.get("serving") or {}
    if srv:
        out(f"serving totals: {srv.get('completed')} completed, "
            f"{srv.get('rejected')} rejected")
    traces = report.get("traces") or {}
    if traces:
        cross = [t for t in traces.values() if len(t.get("hosts") or []) > 1]
        out(f"request traces: {len(traces)} stitched, {len(cross)} "
            f"cross-host, "
            f"{sum(t.get('sheds') or 0 for t in traces.values())} shed "
            f"span(s), "
            f"{sum(t.get('readmits') or 0 for t in traces.values())} "
            "readmit(s)")
        for t in cross[:5]:
            out(f"  rid {t['rid']}: hosts {t['hosts']}, {t['spans']} "
                f"span(s), completed={t['completed']} — one trace_id, "
                "N hosts (waterfalls: tools/request_report.py)")
    elas = report.get("elasticity") or []
    if elas:
        out(f"\nelasticity ({len(elas)} scale event(s), fleet clock):")
        for r in elas:
            out(f"  +{r['t_rel']:8.1f}s  host {r['host']}: "
                f"{r.get('action')}"
                + (f" -> {r['processes']} process(es)"
                   if r.get("processes") is not None else "")
                + (f" epoch {r['epoch']}" if r.get("epoch") is not None
                   else ""))
    live = report.get("hosts_live") or []
    if live:
        peak = max((r.get("hosts_live") or 0) for r in live)
        out(f"\nhosts-live timeline: {len(live)} snapshot(s), peak {peak}")
    auto = report.get("autoscale")
    if auto:
        rows = auto.get("decisions") or []
        out(f"\nautoscale: {len(rows)} decision(s), {auto.get('paired')} "
            f"paired 1:1 with a scale event, "
            f"{auto.get('unattributed_scales')} unattributed scale "
            f"event(s), {auto.get('applied_with_plan_hash')} applied with "
            f"a retuned plan hash, {auto.get('shed_lost')} shed request(s) "
            "lost")
        for r in rows:
            out(f"  +{r['t_rel']:8.1f}s  {r['decision']}: "
                f"{r.get('direction')} {r.get('hosts_from')}"
                f"->{r.get('target_hosts')} host(s) @tick {r.get('tick')} "
                f"— {r.get('signal')}={r.get('value')} vs "
                f"{r.get('threshold')} over {r.get('window_ticks')} "
                "tick(s)"
                + (f"; scaled after {r['lag_s']:.1f}s"
                   if r.get("lag_s") is not None else "; UNPAIRED")
                + (f"; plan {r['applied']['plan_hash']} @epoch "
                   f"{r['applied']['epoch']}"
                   if (r.get("applied") or {}).get("plan_hash") else "")
                + (f"; bundle {r['bundle']}" if r.get("bundle") else ""))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="fleet directory (host<N>/run.jsonl "
                    "families + fleet.jsonl)")
    ap.add_argument("--ledger-name", default="run.jsonl",
                    help="per-host base ledger filename (default "
                    "run.jsonl)")
    ap.add_argument("--json", action="store_true",
                    help="print the FleetLedger report as one JSON object")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.path):
        print(f"{args.path}: not a fleet directory", file=sys.stderr)
        return 1
    fleet = FleetLedger.discover(args.path, ledger_name=args.ledger_name)
    if not fleet.hosts:
        print(f"{args.path}: no host*/ dirs found", file=sys.stderr)
        return 1
    report = fleet.report()
    if args.json:
        print(json.dumps(report, default=str))
    else:
        render(report)
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        raise SystemExit(0)
