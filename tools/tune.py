#!/usr/bin/env python
"""Auto-tune a step plan per device kind from measured artifacts.

    python -m tools.tune --comm-bench comm.json --out plans.json
    python -m tools.tune --comm-bench comm.json --json        # plan JSON
    python -m tools.tune --device-kind "TPU v5 lite" --device-kind v4
    python -m tools.tune --ledger-summary report.json         # refinement
    python -m tools.tune --workload '{"n_params": 9e8, ...}'  # geometry

The ROADMAP item-2 search (tpu_dist.plan.tune): enumerate the step-plan
space (quant x fused kernel x grad buckets x dispatch window x Pallas
block sizes), prune illegal combinations via the plan IR's validator,
score each candidate with the roofline cost model at the device peaks,
fold in ``tools/comm_bench.py --json`` sweep measurements for the
collective costs, and optionally refine with measured trials —
``tools/ledger_report.py --json`` summaries of short plan-stamped runs
(their MFU overrides the analytic score for the matching plan), or a
``trials`` list in the measurement file keyed by knob subsets.

Output: the best-plan-per-device-kind JSON the configs' ``plan`` knob
accepts (``--out`` writes it, ``--json`` prints it). DETERMINISTIC BY
CONTRACT: the same inputs produce byte-identical output (fixed space
order, pure-arithmetic scores, hash tie-breaks) — scripts/lint.sh runs
this twice over a canned measurement file and asserts it. ``--ledger``
appends one ``tune`` event per device kind for run forensics.

Stdlib + tpu_dist.plan only — NO jax: runs on a login host, in CI,
anywhere. The device is named by its kind string (the PEAK_TFLOPS /
PEAK_GBPS table keys); a comm_bench file's ``device_kind`` is the
default.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--comm-bench", action="append", default=[],
                    metavar="JSON",
                    help="tools/comm_bench.py --json sweep file(s); later "
                    "files extend the first's results/trials")
    ap.add_argument("--ledger-summary", action="append", default=[],
                    metavar="JSON",
                    help="tools/ledger_report.py --json summaries of short "
                    "plan-stamped runs (measured refinement)")
    ap.add_argument("--device-kind", action="append", default=[],
                    help="device kind(s) to emit plans for (default: the "
                    "measurement file's device_kind, else 'unknown')")
    ap.add_argument("--workload", default="",
                    help="workload JSON object/string: n_params, "
                    "tokens_per_step, devices, engine (defaults: the r06 "
                    "LM bench geometry)")
    ap.add_argument("--out", default="",
                    help="write the plan JSON here (the config knob's "
                    "input)")
    ap.add_argument("--json", action="store_true",
                    help="print the plan JSON on stdout (the human table "
                    "moves to stderr)")
    ap.add_argument("--top", type=int, default=5,
                    help="how many ranked candidates to show per device "
                    "kind (default 5)")
    ap.add_argument("--ledger", default="",
                    help="append one 'tune' obs.ledger event per device "
                    "kind here")
    args = ap.parse_args(argv)

    from tpu_dist.plan.tune import tune

    workload = json.loads(args.workload) if args.workload else None
    text, results = tune(measurement_files=args.comm_bench,
                         ledger_summary_files=args.ledger_summary,
                         device_kinds=args.device_kind or None,
                         workload=workload)

    say = ((lambda *a, **k: print(*a, file=sys.stderr, **k))
           if args.json else print)
    for kind, res in sorted(results.items()):
        peaks = res["peaks"]
        say(f"{kind}: {res['candidates']} candidate plan(s) at "
            f"{peaks['tflops']:g} TFLOP/s / {peaks['gbps']:g} GB/s"
            + (" (NOMINAL peaks)" if peaks["nominal"] else "")
            + (f"; comm: {res['comm']}" if res["comm"] else
               "; no comm measurements (analytic only)"))
        for i, cand in enumerate(res["ranked"][:max(args.top, 1)]):
            from tpu_dist.plan.ir import plan_knob_summary
            knobs = plan_knob_summary(cand["plan"]) or "(all defaults)"
            say(f"  #{i + 1} {cand['hash']}  {cand['step_s'] * 1e3:9.3f} "
                f"ms/step{' [measured]' if cand['measured'] else ''}  "
                f"{knobs}")
    if args.ledger:
        from tpu_dist.obs.ledger import Ledger

        led = Ledger(args.ledger)
        for kind, res in sorted(results.items()):
            best = res["best"]
            led.emit("tune", device_kind=kind,
                     candidates=res["candidates"],
                     best_hash=best["hash"] if best else None,
                     best_step_s=best["step_s"] if best else None,
                     measured=bool(best and best["measured"]),
                     peaks_nominal=res["peaks"]["nominal"])
        led.close()
        say(f"ledger: {args.ledger}")
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        say(f"plan file: {args.out}")
    if args.json:
        sys.stdout.write(text)
    if not args.out and not args.json:
        say("(no --out/--json: dry run — the table above is the result)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
