#!/usr/bin/env python
"""Communication microbenchmark: ring vs GSPMD collective matmul, and
bucketed vs monolithic gradient sync, swept over sizes.

    JAX_PLATFORMS=cpu python tools/comm_bench.py --cpu-devices 8
    python tools/comm_bench.py --dims 2048,1024,4096 --iters 20   # on TPU
    python tools/comm_bench.py --ledger comm.jsonl                # + records
    python tools/comm_bench.py --json            # machine-readable sweep

Three per-size tables (stdlib + jax only):

1. ``allreduce``  — parallel.collectives.ring_allreduce (the chunked
   ppermute two-pass ring) vs XLA's fused ``psum`` of the same buffer;
2. ``matmul``     — the Megatron column+row projection pair as the ring
   collective matmul (parallel.overlap: AG-matmul + matmul-RS inside
   shard_map) vs the GSPMD einsum pair (sharded weights, XLA-inserted
   collectives), outputs verified allclose per geometry;
3. ``grad sync``  — parallel.overlap.bucketed_grad_sync (independent
   ~bucket-MB reduce-scatter+all-gather collectives, DDP's decomposition)
   vs the monolithic per-leaf psum the engines used through round 7.

``--ledger`` appends obs.ledger ``step`` records whose ``comm_s`` is the
MEASURED per-dispatch seconds (these programs are pure communication, so
device time == comm time — the one place the ledger's comm phase is exact
rather than a probe estimate); query with tools/ledger_report.py.
``--json`` prints the whole sweep as one JSON object on stdout (tables go
to stderr) — the stable input format for the ROADMAP item-3 auto-tuner.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[0.25, 4.0, 32.0],
                    help="buffer sizes for the allreduce + grad-sync sweeps")
    ap.add_argument("--dims", type=str, nargs="+",
                    default=["256,256,1024", "512,512,2048", "512,1024,4096"],
                    help="L,D,F collective-matmul geometries (batch fixed 4)")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--bucket-mb", type=float, default=25.0,
                    help="bucket target for the grad-sync sweep (DDP ~25)")
    ap.add_argument("--ledger", type=str, default="",
                    help="append obs.ledger step records here")
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="force the CPU backend with N virtual devices "
                    "(no-op if the backend is already initialized)")
    ap.add_argument("--json", action="store_true",
                    help="print the sweep as one JSON object on stdout "
                    "(tables move to stderr)")
    return ap.parse_args(argv)


def _timeit(fn, args, iters: int) -> float:
    import jax

    # distlint: disable=DL002 -- compile+warm barrier before the timed window
    jax.block_until_ready(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    # distlint: disable=DL002 -- the timed measurement barrier - benches measure the sync
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def _row(label: str, a: str, b: str, ta: float, tb: float) -> str:
    ratio = ta / tb if tb else float("inf")
    return (f"  {label:<24} {a:>10}: {ta * 1e3:9.3f} ms   "
            f"{b:>10}: {tb * 1e3:9.3f} ms   {a}/{b} = {ratio:5.2f}x")


def bench_allreduce(mesh, sizes_mb, iters, emit, say=print, results=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from tpu_dist._compat import shard_map
    from tpu_dist.parallel.collectives import ring_allreduce
    from tpu_dist.parallel.mesh import DATA_AXIS

    n = mesh.devices.size
    say(f"\nallreduce (sum across {n} devices, per-device buffer):")
    for mb in sizes_mb:
        elems = max(n, int(mb * 1e6 / 4))
        x = jnp.ones((elems,), jnp.float32)

        def ring(v):
            return ring_allreduce(v, DATA_AXIS, n)

        def fused(v):
            return jax.lax.psum(v, DATA_AXIS)

        wrap = lambda f: jax.jit(shard_map(
            f, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))
        t_ring = _timeit(wrap(ring), (x,), iters)
        t_psum = _timeit(wrap(fused), (x,), iters)
        say(_row(f"{mb:g} MB", "ring", "psum", t_ring, t_psum))
        if results is not None:
            results.append({"bench": "allreduce", "size_mb": mb,
                            "bytes": elems * 4, "ring_s": t_ring,
                            "psum_s": t_psum})
        emit(f"allreduce_{mb:g}mb", t_ring, elems * 4)


def bench_collective_matmul(mesh, dims, iters, emit, say=print,
                            results=None):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from tpu_dist._compat import shard_map
    from tpu_dist.parallel.mesh import MODEL_AXIS
    from tpu_dist.parallel.overlap import (ring_allgather_matmul,
                                           ring_matmul_reduce_scatter)

    n = mesh.devices.size
    b = 4
    say(f"\ncollective matmul (column+row Megatron pair over {n} shards, "
        f"batch {b}):")
    for spec in dims:
        # distlint: disable=DL002 -- host string parsing of the CLI dims spec, not a device fetch
        L, D, F = (int(v) for v in spec.split(","))
        if L % n or F % n or D % n:
            say(f"  {spec}: skipped (dims must divide the axis size {n})")
            continue
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(b, L, D)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(D, F)) * 0.05, jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(F, D)) * 0.05, jnp.float32)

        def pair_ring(xs, a, c):
            h = ring_allgather_matmul(xs, a, MODEL_AXIS)
            return ring_matmul_reduce_scatter(h, c, MODEL_AXIS)

        ring = jax.jit(shard_map(
            pair_ring, mesh=mesh,
            in_specs=(P(None, MODEL_AXIS, None), P(None, MODEL_AXIS),
                      P(MODEL_AXIS, None)),
            out_specs=P(None, MODEL_AXIS, None), check_vma=False))

        gspmd = jax.jit(
            lambda xs, a, c: (xs @ a) @ c,
            in_shardings=(NamedSharding(mesh, P(None, MODEL_AXIS, None)),
                          NamedSharding(mesh, P(None, MODEL_AXIS)),
                          NamedSharding(mesh, P(MODEL_AXIS, None))),
            out_shardings=NamedSharding(mesh, P(None, MODEL_AXIS, None)))

        # distlint: disable=DL002 -- ring-vs-GSPMD parity check on drained host copies
        np.testing.assert_allclose(np.asarray(ring(x, w1, w2)),
                                   np.asarray(gspmd(x, w1, w2)),
                                   rtol=2e-4, atol=2e-4)
        t_ring = _timeit(ring, (x, w1, w2), iters)
        t_gspmd = _timeit(gspmd, (x, w1, w2), iters)
        say(_row(f"L{L} D{D} F{F}", "ring", "gspmd", t_ring, t_gspmd))
        if results is not None:
            results.append({"bench": "collective_matmul",
                            "dims": [L, D, F], "bytes": b * L * D * 4,
                            "ring_s": t_ring, "gspmd_s": t_gspmd})
        emit(f"matmul_L{L}_D{D}_F{F}", t_ring, b * L * D * 4)


def bench_grad_sync(mesh, sizes_mb, bucket_mb, iters, emit, say=print,
                    results=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from tpu_dist._compat import shard_map
    from tpu_dist.parallel.mesh import DATA_AXIS
    from tpu_dist.parallel.overlap import bucketed_grad_sync

    n = mesh.devices.size
    say(f"\ngradient sync across {n} replicas "
        f"(bucketed @ {bucket_mb:g} MB vs monolithic psum):")
    for mb in sizes_mb:
        elems = max(n, int(mb * 1e6 / 4))
        # a realistic ragged tree: a big embedding-ish leaf + smaller ones
        tree = {"emb": jnp.ones((elems // 2,), jnp.float32),
                "w1": jnp.ones((elems // 4,), jnp.float32),
                "w2": jnp.ones((elems // 8,), jnp.float32),
                "rest": jnp.ones((elems - elems // 2 - elems // 4
                                  - elems // 8,), jnp.float32)}

        def bucketed(t):
            return bucketed_grad_sync(t, DATA_AXIS, bucket_mb, mean=True,
                                      axis_size=n)

        def monolithic(t):
            return jax.tree.map(lambda g: jax.lax.pmean(g, DATA_AXIS), t)

        wrap = lambda f: jax.jit(shard_map(
            f, mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False))
        t_b = _timeit(wrap(bucketed), (tree,), iters)
        t_m = _timeit(wrap(monolithic), (tree,), iters)
        say(_row(f"{mb:g} MB tree", "bucketed", "monolithic", t_b, t_m))
        if results is not None:
            results.append({"bench": "grad_sync", "size_mb": mb,
                            "bucket_mb": bucket_mb, "bytes": elems * 4,
                            "bucketed_s": t_b, "monolithic_s": t_m})
        emit(f"grad_sync_{mb:g}mb", t_b, elems * 4)


def main(argv=None) -> int:
    args = _args(argv)
    if args.cpu_devices:
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
            from tpu_dist._compat import set_cpu_device_count
            set_cpu_device_count(args.cpu_devices)
        except Exception as e:  # backend already live (e.g. under pytest)
            print(f"--cpu-devices: backend already initialized ({e}); "
                  "using the existing devices", file=sys.stderr)
    import jax
    from tpu_dist.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh

    n = jax.device_count()
    if n < 2:
        print(f"comm_bench needs >= 2 devices (have {n}); run with "
              "JAX_PLATFORMS=cpu --cpu-devices 8", file=sys.stderr)
        return 1
    data_mesh = make_mesh((n,), (DATA_AXIS,))
    model_mesh = make_mesh((n,), (MODEL_AXIS,))
    # --json: the object owns stdout, the human tables move to stderr
    say = ((lambda *a, **k: print(*a, file=sys.stderr, **k))
           if args.json else print)
    results: list = []
    say(f"devices: {n} x {jax.devices()[0].device_kind}")

    ledger = None
    step_i = 0
    if args.ledger:
        from tpu_dist.obs import Ledger

        ledger = Ledger(args.ledger)
        ledger.emit("run_start", kind="comm_bench",
                    config={"sizes_mb": args.sizes_mb, "dims": args.dims,
                            "bucket_mb": args.bucket_mb,
                            "iters": args.iters},
                    mesh={"data": n}, process_count=jax.process_count(),
                    devices=sorted({d.device_kind for d in
                                    jax.local_devices()}))

    def emit(label, seconds, nbytes):
        nonlocal step_i
        if ledger is None:
            return
        # pure-communication programs: device time IS comm time, so the
        # comm phase here is measured, not estimated
        ledger.emit("step", step=step_i, loss=None,
                    throughput=round(nbytes / seconds / 1e9, 3),
                    unit="GB/s", data_s=0.0, dispatch_s=0.0,
                    device_s=round(seconds, 6), comm_s=round(seconds, 6),
                    mfu=None, label=label)
        step_i += 1

    t0 = time.perf_counter()
    bench_allreduce(data_mesh, args.sizes_mb, args.iters, emit,
                    say=say, results=results)
    bench_collective_matmul(model_mesh, args.dims, args.iters, emit,
                            say=say, results=results)
    bench_grad_sync(data_mesh, args.sizes_mb, args.bucket_mb, args.iters,
                    emit, say=say, results=results)
    if ledger is not None:
        ledger.emit("run_end", steps=step_i,
                    seconds=round(time.perf_counter() - t0, 3))
        ledger.close()
        say(f"\nledger: {args.ledger}")
    if args.json:
        import json

        print(json.dumps({
            "devices": n,
            "device_kind": jax.devices()[0].device_kind,
            "iters": args.iters,
            "bucket_mb": args.bucket_mb,
            "seconds": round(time.perf_counter() - t0, 3),
            "results": results,
        }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
