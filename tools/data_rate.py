#!/usr/bin/env python
"""Host data-path throughput: how fast can each dataset flavor feed batches?

Measures ``get_batch`` images/sec for (a) the in-memory ArrayDataset gather
(native C++ row memcpy when built) and (b) the lazy ImageFolder JPEG-decode
path, against the device step rate the host must keep up with (BASELINE.md:
~2,031 img/s/chip for ResNet-50 @ 224px). The VERDICT r2 note was that the
ImageFolder decode rate was never measured — this makes it a one-command
number. A synthetic ImageFolder tree (PIL-written JPEGs) is generated under
--root when absent, so the tool runs in the zero-egress environment.

Usage:
    python tools/data_rate.py                 # both flavors, batch 256
    python tools/data_rate.py --images 512 --batch 128 --workers 16
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_synthetic_imagefolder(root: str, n_images: int, size: int) -> str:
    """root/train/<class>/<img>.jpg with random pixels at the SOURCE size
    (realistic ImageNet photos are ~500px, decoded down to the model size);
    returns split dir."""
    import numpy as np

    try:
        from PIL import Image
    except ImportError:
        raise SystemExit("PIL unavailable — cannot build the JPEG tree")
    split = os.path.join(root, "train")
    rng = np.random.default_rng(0)
    for c in range(4):
        cdir = os.path.join(split, f"class{c}")
        os.makedirs(cdir, exist_ok=True)
        for i in range(n_images // 4):
            p = os.path.join(cdir, f"img{i}.jpg")
            if not os.path.exists(p):
                arr = rng.integers(0, 255, (size, size, 3), np.uint8)
                Image.fromarray(arr).save(p, quality=85)
    return split


def _rate(ds, batch: int, seconds: float = 3.0) -> float:
    import numpy as np

    n = len(ds.labels) if hasattr(ds, "labels") else len(ds)
    rng = np.random.default_rng(1)
    # warm (page cache, thread pool spin-up)
    ds.get_batch(rng.integers(0, n, batch))
    done = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        ds.get_batch(rng.integers(0, n, batch))
        done += batch
    return done / (time.perf_counter() - t0)


def main():
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default="/tmp/tpu_dist_synth_imagefolder")
    ap.add_argument("--images", type=int, default=256)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--src-size", type=int, default=500,
                    help="stored JPEG size (ImageNet photos average ~500px)")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=3.0)
    args = ap.parse_args()

    from tpu_dist import _native
    from tpu_dist.data.datasets import _synthetic
    from tpu_dist.data.imagefolder import ImageFolderDataset

    arr = _synthetic(args.images, (args.size, args.size, 3), 4,
                     proto_seed=0, sample_seed=1, name="synth-224")
    # numpy fallback first (force the library off), then the native path —
    # the VERDICT r3 #5 comparison that pins where assembly time goes
    with _native.numpy_fallback():
        numpy_rate = _rate(arr, args.batch, args.seconds)
    print(f"ArrayDataset gather, numpy fallback ({args.size}px): "
          f"{numpy_rate:,.0f} img/s", file=sys.stderr)
    arr_rate = None
    if _native.available():
        arr_rate = _rate(arr, args.batch, args.seconds)
        print(f"ArrayDataset gather, native csrc ({args.size}px): "
              f"{arr_rate:,.0f} img/s", file=sys.stderr)
    else:
        print("native gather library unavailable (no toolchain?)",
              file=sys.stderr)

    split = _make_synthetic_imagefolder(
        args.root + f"_{args.src_size}", args.images, args.src_size)
    folder = ImageFolderDataset(split, size=args.size, workers=args.workers)
    # PIL path first (numpy_fallback also disables native decode), then the
    # native libjpeg decoder (csrc/decode.cpp)
    with _native.numpy_fallback():
        pil_rate = _rate(folder, args.batch, args.seconds)
    print(f"ImageFolder JPEG decode, PIL ({args.workers} workers, "
          f"{args.src_size}px -> {args.size}px): {pil_rate:,.0f} img/s",
          file=sys.stderr)
    dec_rate = None
    if _native.decode_available():
        dec_rate = _rate(folder, args.batch, args.seconds)
        print(f"ImageFolder JPEG decode, native libjpeg ({args.workers} "
              f"workers): {dec_rate:,.0f} img/s", file=sys.stderr)
    else:
        print("native decode unavailable (no libjpeg at build time)",
              file=sys.stderr)

    print(json.dumps({
        "metric": "host_data_path_images_per_sec",
        "array_gather_native": (round(arr_rate, 1)
                                if arr_rate is not None else None),
        "array_gather_numpy": round(numpy_rate, 1),
        "imagefolder_decode_pil": round(pil_rate, 1),
        "imagefolder_decode_native": (round(dec_rate, 1)
                                      if dec_rate is not None else None),
        "batch": args.batch, "image_size": args.size,
        "src_size": args.src_size,
        "workers": args.workers,
        "device_rate_note": "ResNet-50 @224px device rate ~2031 img/s/chip "
                            "(BASELINE.md); decode below that means the host "
                            "input pipeline is the binding constraint",
    }))


if __name__ == "__main__":
    main()
