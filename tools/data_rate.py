#!/usr/bin/env python
"""Host data-path throughput: how fast can each dataset flavor feed batches?

Measures ``get_batch`` images/sec for (a) the in-memory ArrayDataset gather
(native C++ row memcpy when built) and (b) the lazy ImageFolder JPEG-decode
path, against the device step rate the host must keep up with (BASELINE.md:
~2,031 img/s/chip for ResNet-50 @ 224px). The VERDICT r2 note was that the
ImageFolder decode rate was never measured — this makes it a one-command
number. A synthetic ImageFolder tree (PIL-written JPEGs) is generated under
--root when absent, so the tool runs in the zero-egress environment.

Also probes the round-9 DevicePrefetcher standalone: achieved ``data_s``
(consumer queue wait) vs the un-overlapped inline copy time for the same
uploads, so the overlap win is a number independent of any training run
(--prefetch-batches/--prefetch-mb/--step-ms; 0 batches disables).

Usage:
    python tools/data_rate.py                 # both flavors, batch 256
    python tools/data_rate.py --images 512 --batch 128 --workers 16
    python tools/data_rate.py --prefetch-batches 32 --step-ms 50
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_synthetic_imagefolder(root: str, n_images: int, size: int) -> str:
    """root/train/<class>/<img>.jpg with random pixels at the SOURCE size
    (realistic ImageNet photos are ~500px, decoded down to the model size);
    returns split dir."""
    import numpy as np

    try:
        from PIL import Image
    except ImportError:
        raise SystemExit("PIL unavailable — cannot build the JPEG tree")
    split = os.path.join(root, "train")
    rng = np.random.default_rng(0)
    for c in range(4):
        cdir = os.path.join(split, f"class{c}")
        os.makedirs(cdir, exist_ok=True)
        for i in range(n_images // 4):
            p = os.path.join(cdir, f"img{i}.jpg")
            if not os.path.exists(p):
                arr = rng.integers(0, 255, (size, size, 3), np.uint8)
                Image.fromarray(arr).save(p, quality=85)
    return split


def _rate(ds, batch: int, seconds: float = 3.0) -> float:
    import numpy as np

    n = len(ds.labels) if hasattr(ds, "labels") else len(ds)
    rng = np.random.default_rng(1)
    # warm (page cache, thread pool spin-up)
    ds.get_batch(rng.integers(0, n, batch))
    done = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        ds.get_batch(rng.integers(0, n, batch))
        done += batch
    return done / (time.perf_counter() - t0)


def _prefetch_overlap(batch_mb: float, batches: int, step_ms: float) -> dict:
    """Overlap efficiency of data.loader.DevicePrefetcher, standalone.

    Feeds ``batches`` host arrays of ``batch_mb`` MB through the prefetcher
    while the consumer runs a calibrated ~``step_ms`` device step between
    fetches (a jitted matmul loop — real dispatch+sync so GIL/transfer
    interactions are the engine's), and compares the achieved consumer wait
    (the engines' ``data_s``) against the un-overlapped world: the same
    uploads timed inline on the consumer thread. ``overlap_efficiency`` is
    the prefetcher's own ledger (1 - wait/put); ``hidden_frac`` is the
    end-to-end claim — what fraction of the inline copy cost disappeared
    from the consumer's critical path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_dist.data.loader import DevicePrefetcher

    n = max(1, int(batch_mb * 1e6) // 4)
    rng = np.random.default_rng(0)
    host = [rng.random(n).astype(np.float32) for _ in range(min(batches, 4))]
    feed = [host[i % len(host)] for i in range(batches)]

    # calibrate a jitted-matmul step to ~step_ms of device time
    a = jnp.ones((512, 512), jnp.float32)
    mm = jax.jit(lambda x: x @ x)
    mm(a).block_until_ready()
    t0 = time.perf_counter()
    mm(a).block_until_ready()
    one = max(time.perf_counter() - t0, 1e-6)
    reps = max(1, int(step_ms / 1e3 / one))

    def step():
        for _ in range(reps):
            out = mm(a)
        # distlint: disable=DL002 -- the calibrated barrier IS the simulated device step this probe times against
        out.block_until_ready()

    jax.device_put(feed[0]).block_until_ready()     # warm the transfer path
    inline_s = 0.0
    for b in feed:                                  # the un-prefetched world
        t0 = time.perf_counter()
        # distlint: disable=DL002, DL008 -- deliberately un-overlapped inline copy: the baseline this probe measures the prefetcher against
        jax.device_put(b).block_until_ready()
        inline_s += time.perf_counter() - t0
        step()

    pf = DevicePrefetcher(iter(feed))               # the overlapped world
    for _ in pf:
        step()
    stats = pf.stats()
    hidden = None
    if inline_s > 0:
        hidden = max(0.0, min(1.0, 1.0 - stats["wait_s"] / inline_s))
    return {"batches": batches, "batch_mb": batch_mb,
            "step_ms": step_ms,
            "inline_copy_s": round(inline_s, 6),
            "prefetch_put_s": stats["put_s"],
            "prefetch_wait_s": stats["wait_s"],      # == achieved data_s
            "overlap_efficiency": stats["overlap_efficiency"],
            "hidden_frac": hidden}


def main():
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default="/tmp/tpu_dist_synth_imagefolder")
    ap.add_argument("--images", type=int, default=256)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--src-size", type=int, default=500,
                    help="stored JPEG size (ImageNet photos average ~500px)")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--prefetch-batches", type=int, default=16,
                    help="batches for the DevicePrefetcher overlap probe "
                         "(0 disables it)")
    ap.add_argument("--prefetch-mb", type=float, default=8.0,
                    help="host batch size (MB) fed to the overlap probe")
    ap.add_argument("--step-ms", type=float, default=20.0,
                    help="simulated device-step duration between fetches")
    args = ap.parse_args()

    from tpu_dist import _native
    from tpu_dist.data.datasets import _synthetic
    from tpu_dist.data.imagefolder import ImageFolderDataset

    arr = _synthetic(args.images, (args.size, args.size, 3), 4,
                     proto_seed=0, sample_seed=1, name="synth-224")
    # numpy fallback first (force the library off), then the native path —
    # the VERDICT r3 #5 comparison that pins where assembly time goes
    with _native.numpy_fallback():
        numpy_rate = _rate(arr, args.batch, args.seconds)
    print(f"ArrayDataset gather, numpy fallback ({args.size}px): "
          f"{numpy_rate:,.0f} img/s", file=sys.stderr)
    arr_rate = None
    if _native.available():
        arr_rate = _rate(arr, args.batch, args.seconds)
        print(f"ArrayDataset gather, native csrc ({args.size}px): "
              f"{arr_rate:,.0f} img/s", file=sys.stderr)
    else:
        print("native gather library unavailable (no toolchain?)",
              file=sys.stderr)

    split = _make_synthetic_imagefolder(
        args.root + f"_{args.src_size}", args.images, args.src_size)
    folder = ImageFolderDataset(split, size=args.size, workers=args.workers)
    # PIL path first (numpy_fallback also disables native decode), then the
    # native libjpeg decoder (csrc/decode.cpp)
    with _native.numpy_fallback():
        pil_rate = _rate(folder, args.batch, args.seconds)
    print(f"ImageFolder JPEG decode, PIL ({args.workers} workers, "
          f"{args.src_size}px -> {args.size}px): {pil_rate:,.0f} img/s",
          file=sys.stderr)
    dec_rate = None
    if _native.decode_available():
        dec_rate = _rate(folder, args.batch, args.seconds)
        print(f"ImageFolder JPEG decode, native libjpeg ({args.workers} "
              f"workers): {dec_rate:,.0f} img/s", file=sys.stderr)
    else:
        print("native decode unavailable (no libjpeg at build time)",
              file=sys.stderr)

    prefetch = None
    if args.prefetch_batches > 0:
        prefetch = _prefetch_overlap(args.prefetch_mb, args.prefetch_batches,
                                     args.step_ms)
        print(f"DevicePrefetcher overlap ({args.prefetch_batches} x "
              f"{args.prefetch_mb:g} MB, {args.step_ms:g} ms step): "
              f"data_s {prefetch['prefetch_wait_s']:.4f}s vs inline copy "
              f"{prefetch['inline_copy_s']:.4f}s — "
              f"{(prefetch['hidden_frac'] or 0) * 100:.0f}% of the copy "
              "cost hidden behind compute", file=sys.stderr)

    print(json.dumps({
        "metric": "host_data_path_images_per_sec",
        "array_gather_native": (round(arr_rate, 1)
                                if arr_rate is not None else None),
        "array_gather_numpy": round(numpy_rate, 1),
        "imagefolder_decode_pil": round(pil_rate, 1),
        "imagefolder_decode_native": (round(dec_rate, 1)
                                      if dec_rate is not None else None),
        "batch": args.batch, "image_size": args.size,
        "src_size": args.src_size,
        "workers": args.workers,
        "prefetch": prefetch,
        "device_rate_note": "ResNet-50 @224px device rate ~2031 img/s/chip "
                            "(BASELINE.md); decode below that means the host "
                            "input pipeline is the binding constraint",
    }))


if __name__ == "__main__":
    main()
