"""CI-grade output formats: SARIF 2.1.0 and the suppression-debt report.

SARIF (Static Analysis Results Interchange Format) is what CI code-scanning
surfaces ingest natively; emitting it makes distlint findings first-class
review annotations instead of a log to grep. The debt report is the other
half of the suppression contract: every ``# distlint: disable`` carries a
reason, and ``--debt`` inventories them (per-rule counts, locations, file
age, staleness) so a handful of reasoned pins never silently grows into a
pile nobody audits.

Stdlib-only like the rest of the package; ``git`` is invoked for file ages
when available and skipped silently when not (CI tarballs, no-git trees).
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import Dict, List, Optional, Tuple

from tools.distlint.core import (META_RULE, LintResult, iter_python_files,
                                 parse_suppressions)
from tools.distlint.rules import RULES, RULES_BY_ID

SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"
SARIF_VERSION = "2.1.0"

# SARIF 'level' per severity tier ('warn' is called 'warning' there)
_SARIF_LEVEL = {"error": "error", "warn": "warning"}


def severity_of(rule_id: str) -> str:
    """'error' | 'warn' for a rule id (DL000 meta findings are errors:
    a malformed suppression or unparseable file must gate)."""
    if rule_id == META_RULE:
        return "error"
    r = RULES_BY_ID.get(rule_id)
    return getattr(r, "severity", "error") if r is not None else "error"


def split_by_severity(result: LintResult) -> Tuple[list, list]:
    """(error_findings, warn_findings)."""
    err = [f for f in result.findings if severity_of(f.rule) == "error"]
    warn = [f for f in result.findings if severity_of(f.rule) == "warn"]
    return err, warn


def to_sarif(result: LintResult) -> dict:
    """Minimal valid SARIF 2.1.0 log: one run, the full rule catalog as
    tool metadata, one result per finding (1-based columns, per spec)."""
    rules_meta = [{
        "id": META_RULE,
        "shortDescription": {"text": "malformed suppression / "
                                     "unparseable file"},
        "defaultConfiguration": {"level": "error"},
    }]
    for r in RULES:
        rules_meta.append({
            "id": r.id,
            "shortDescription": {"text": r.title},
            "fullDescription": {"text": r.rationale},
            "defaultConfiguration": {
                "level": _SARIF_LEVEL[getattr(r, "severity", "error")]},
        })
    results = []
    for f in result.findings:
        results.append({
            "ruleId": f.rule,
            "level": _SARIF_LEVEL[severity_of(f.rule)],
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                },
            }],
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                # informationUri is typed as an ABSOLUTE uri in the SARIF
                # schema; a relative README anchor would make strict
                # consumers reject the whole artifact, so it is omitted
                "name": "distlint",
                "rules": rules_meta,
            }},
            # SRCROOT is deliberately left undeclared (no
            # originalUriBaseIds): consumers resolve the repo-relative
            # URIs against their own checkout, GitHub-code-scanning
            # style; declaring file:/// would point at filesystem root
            "results": results,
        }],
    }


# ------------------------------------------------------------------ debt
def _git_file_age_days(root: str, rel: str) -> Optional[float]:
    """Days since the last commit touching ``rel`` (None when git is
    absent, the tree is not a repo, or the file is uncommitted)."""
    try:
        out = subprocess.run(
            ["git", "log", "-1", "--format=%ct", "--", rel],
            cwd=root, capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.SubprocessError):
        return None
    ts = out.stdout.strip()
    if out.returncode != 0 or not ts:
        return None
    try:
        return max(0.0, (time.time() - int(ts)) / 86400.0)
    except ValueError:
        return None


def collect_debt(paths, root: str, result: Optional[LintResult] = None,
                 with_ages: bool = True) -> dict:
    """Inventory every suppression comment under ``paths``.

    Returns ``{"entries": [...], "by_rule": {rule: count},
    "stale": [...]}``. When a ``result`` from the same surface is given,
    suppressions that matched no finding are listed as stale — a stale
    pin is a rule the tree no longer violates, i.e. deletable debt.
    ``with_ages=False`` skips the per-file ``git log`` subprocesses
    (tests that only assert counts/staleness stay cheap)."""
    active: set = set()
    if result is not None:
        active = {(f.path, s.comment_line) for f, s in result.suppressed}
    entries: List[dict] = []
    by_rule: Dict[str, int] = {}
    age_cache: Dict[str, Optional[float]] = {}
    for path in iter_python_files(paths, root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        sups, _ = parse_suppressions(src)
        if with_ages and sups and rel not in age_cache:
            age_cache[rel] = _git_file_age_days(root, rel)
        for s in sups:
            for rule in s.rules:
                by_rule[rule] = by_rule.get(rule, 0) + 1
            entries.append({
                "path": rel, "line": s.comment_line,
                "rules": list(s.rules), "reason": s.reason,
                "file_age_days": age_cache.get(rel),
                "stale": (result is not None
                          and (rel, s.comment_line) not in active),
            })
    entries.sort(key=lambda e: (e["path"], e["line"]))
    return {"entries": entries, "by_rule": dict(sorted(by_rule.items())),
            "stale": [e for e in entries if e["stale"]]}


def render_debt(debt: dict) -> str:
    """Human rendering of :func:`collect_debt` (the advisory print
    scripts/lint.sh tacks onto the gate)."""
    entries = debt["entries"]
    lines = [f"distlint debt: {len(entries)} suppression(s)"]
    if not entries:
        return lines[0]
    counts = "  ".join(f"{r} x{n}" for r, n in debt["by_rule"].items())
    lines.append(f"  per rule: {counts}")
    for e in entries:
        age = (f"{e['file_age_days']:.0f}d" if e["file_age_days"]
               is not None else "?")
        mark = "  [STALE: matched no finding]" if e["stale"] else ""
        lines.append(f"  {e['path']}:{e['line']}  "
                     f"{','.join(e['rules'])}  (file age {age})  "
                     f"-- {e['reason']}{mark}")
    n_stale = len(debt["stale"])
    if n_stale:
        lines.append(f"  {n_stale} stale suppression(s) above can likely "
                     "be deleted")
    return "\n".join(lines)
