"""distlint — AST-based SPMD-correctness + concurrency linter for tpu_dist.

Stdlib-only (ast + tokenize, no jax import): statically catches the
distributed failure classes the runtime watchdog can only report after
they hang a pod — collectives under host-divergent guards, blocking host
syncs on the hot step path, typo'd mesh axis names, untraced side effects
inside jitted code, PRNG key reuse, ledger schema drift, donated-buffer
reuse — plus, on the cross-file call graph + reachability engine
(:class:`~tools.distlint.core.CallGraph`), the DL1xx concurrency/signal-
safety family: plain-Lock-on-signal-path self-deadlocks (the PR-5 Ledger
SIGTERM class), blocking I/O under emit locks, non-daemon threads nobody
joins, and unsafe signal-handler bodies.

CLI::

    python -m tools.distlint                  # full surface, error-tier gate
    python -m tools.distlint --format sarif   # SARIF 2.1.0
    python -m tools.distlint --debt           # suppression inventory
    python -m tools.distlint --json --select DL002,DL101 tpu_dist

API::

    from tools.distlint import lint_files
    result = lint_files(["tpu_dist", "tools", "tests", "scripts",
                         "bench.py"])
    assert result.findings == []

Suppressions are inline, with a REQUIRED reason::

    rows = np.asarray(x)  # distlint: disable=DL002 -- host array, not device

See tools/distlint/rules.py for the rule catalog (with severity tiers),
tools/distlint/report.py for SARIF/debt, and README.md ("Static
analysis") for the rule table.
"""

from tools.distlint.core import (CallGraph, Finding, LintResult, Project,
                                 REPO_ROOT, graph_scope, lint_files,
                                 load_callgraph, load_event_schema,
                                 load_mesh_axes, parse_suppressions)
from tools.distlint.report import (collect_debt, render_debt, severity_of,
                                   split_by_severity, to_sarif)
from tools.distlint.rules import RULES, RULES_BY_ID

__all__ = ["CallGraph", "Finding", "LintResult", "Project", "REPO_ROOT",
           "RULES", "RULES_BY_ID", "collect_debt", "graph_scope",
           "lint_files", "load_callgraph", "load_event_schema",
           "load_mesh_axes", "parse_suppressions", "render_debt",
           "severity_of", "split_by_severity", "to_sarif"]
