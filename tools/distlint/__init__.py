"""distlint — AST-based SPMD-correctness linter for the tpu_dist tree.

Stdlib-only (ast + tokenize, no jax import): statically catches the
distributed failure classes the runtime watchdog can only report after
they hang a pod — collectives under host-divergent guards, blocking host
syncs in the engines' hot loops, typo'd mesh axis names, untraced side
effects inside jitted code, PRNG key reuse, and ledger schema drift.

CLI::

    python -m tools.distlint tpu_dist tools bench.py
    python -m tools.distlint --json --select DL002,DL004 tpu_dist

API::

    from tools.distlint import lint_files
    result = lint_files(["tpu_dist", "tools", "bench.py"])
    assert result.findings == []

Suppressions are inline, with a REQUIRED reason::

    rows = np.asarray(x)  # distlint: disable=DL002 -- host array, not device

See tools/distlint/rules.py for the rule catalog and README.md
("Static analysis") for the rule table.
"""

from tools.distlint.core import (Finding, LintResult, Project, REPO_ROOT,
                                 lint_files, load_event_schema,
                                 load_mesh_axes, parse_suppressions)
from tools.distlint.rules import RULES, RULES_BY_ID

__all__ = ["Finding", "LintResult", "Project", "REPO_ROOT", "RULES",
           "RULES_BY_ID", "lint_files", "load_event_schema",
           "load_mesh_axes", "parse_suppressions"]
