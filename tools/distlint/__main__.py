"""CLI: ``python -m tools.distlint [paths...]``.

Exit code 1 when any unsuppressed ERROR-tier finding exists (warn-tier
findings print but never gate — scripts/lint.sh relies on this), 2 on
usage errors, 0 otherwise. The default path set is the full acceptance
surface — tpu_dist, tools (the linter lints itself), tests, scripts,
bench.py — and the tree stays pinned at zero findings.

Formats: ``--format human|json|sarif`` (``--json`` is a legacy alias);
``--sarif-out FILE`` additionally writes the SARIF artifact beside any
format, which is how CI gets a code-scanning upload from the same run.
``--debt`` prints the suppression inventory (per-rule counts, reasons,
file age, staleness) instead of gating — advisory by design.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.distlint.core import REPO_ROOT, lint_files
from tools.distlint.report import (collect_debt, render_debt,
                                   split_by_severity, to_sarif)
from tools.distlint.rules import RULES

DEFAULT_PATHS = ["tpu_dist", "tools", "tests", "scripts", "bench.py"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.distlint",
        description="AST-based SPMD-correctness and concurrency-safety "
                    "linter (stdlib-only).")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="repo root (mesh axes / ledger schema / call "
                         "graph are loaded relative to it)")
    ap.add_argument("--select", default=None, metavar="DL001,DL002",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--format", default=None, dest="fmt",
                    choices=("human", "json", "sarif"),
                    help="output format (default: human)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="legacy alias for --format json")
    ap.add_argument("--sarif-out", default=None, metavar="FILE",
                    help="also write a SARIF 2.1.0 artifact to FILE")
    ap.add_argument("--debt", action="store_true",
                    help="print the suppression-debt inventory (advisory: "
                         "always exits 0)")
    ap.add_argument("--with-debt", action="store_true",
                    help="append the debt inventory after the findings "
                         "summary of the SAME run (what scripts/lint.sh "
                         "uses — no second full lint)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            sev = getattr(r, "severity", "error")
            print(f"{r.id}  [{sev}]  {r.title}\n       {r.rationale}")
        return 0

    fmt = args.fmt or ("json" if args.as_json else "human")
    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    if select:
        known = {r.id for r in RULES}
        bad = sorted(set(select) - known)
        if bad:
            print(f"distlint: unknown rule id(s) {bad} "
                  f"(known: {sorted(known)})", file=sys.stderr)
            return 2
    paths = args.paths or DEFAULT_PATHS
    try:
        result = lint_files(paths, root=args.root, select=select)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.sarif_out:   # before --debt: the artifact writes either way
        with open(args.sarif_out, "w") as f:
            json.dump(to_sarif(result), f, indent=2, sort_keys=True)

    # staleness is only decidable against a FULL-rule result: under
    # --select, pins for unselected rules match no finding by
    # construction and would all be mislabeled deletable debt
    debt_result = result if select is None else None

    if args.debt:
        debt = collect_debt(paths, args.root, debt_result)
        if fmt == "json":
            print(json.dumps(debt, indent=2, sort_keys=True))
        else:
            print(render_debt(debt))
        return 0

    errors, warns = split_by_severity(result)
    if fmt == "sarif":
        print(json.dumps(to_sarif(result), indent=2, sort_keys=True))
    elif fmt == "json":
        payload = result.to_json()
        payload["errors"] = len(errors)
        payload["warnings"] = len(warns)
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(f.render())
        print(f"distlint: {len(errors)} error(s), {len(warns)} "
              f"warning(s), {len(result.suppressed)} suppressed, "
              f"{result.files_checked} file(s) checked")
    if args.with_debt:
        # advisory inventory from THIS run's result — no second sweep;
        # goes to stderr under json/sarif so stdout stays parseable
        print(render_debt(collect_debt(paths, args.root, debt_result)),
              file=sys.stderr if fmt != "human" else sys.stdout)
    return 1 if errors else 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:   # `... | head` closed the pipe: not an error
        raise SystemExit(0)
