"""CLI: ``python -m tools.distlint [paths...]``.

Exits non-zero when any unsuppressed finding exists — wire it into CI
(scripts/lint.sh) and the tree stays pinned at zero. The default path set
is the acceptance surface: tpu_dist, tools, bench.py.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.distlint.core import REPO_ROOT, lint_files
from tools.distlint.rules import RULES

DEFAULT_PATHS = ["tpu_dist", "tools", "bench.py"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.distlint",
        description="AST-based SPMD-correctness linter (stdlib-only).")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="repo root (mesh axes / ledger schema are loaded "
                         "relative to it)")
    ap.add_argument("--select", default=None, metavar="DL001,DL002",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output (findings + suppressed)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            print(f"{r.id}  {r.title}\n       {r.rationale}")
        return 0

    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    if select:
        known = {r.id for r in RULES}
        bad = sorted(set(select) - known)
        if bad:
            print(f"distlint: unknown rule id(s) {bad} "
                  f"(known: {sorted(known)})", file=sys.stderr)
            return 2
    try:
        result = lint_files(args.paths or DEFAULT_PATHS, root=args.root,
                            select=select)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(result.to_json(), indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(f.render())
        print(f"distlint: {len(result.findings)} finding(s), "
              f"{len(result.suppressed)} suppressed, "
              f"{result.files_checked} file(s) checked")
    return 1 if result.findings else 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:   # `... | head` closed the pipe: not an error
        raise SystemExit(0)
