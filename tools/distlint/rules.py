"""The distlint rule set: SPMD-correctness hazards visible in source.

Every rule is a pure function of (FileContext, Project) returning
:class:`~tools.distlint.core.Finding` objects. The hazards are the failure
classes the PR 2 watchdog can only report AFTER they hang a pod at runtime;
GSPMD single-program multi-host JAX makes them statically visible:

DL001  collectives/checkpoints reachable only under host-divergent guards
       (``process_index() == 0``-style) — the other hosts never enter the
       collective and the pod deadlocks.
DL002  blocking host syncs inside the engines' hot step loops — each one
       drains the async-dispatch queue and serializes the device.
DL003  axis-name literals in PartitionSpec/collective calls validated
       against the mesh axes declared in tpu_dist/parallel/mesh.py —
       a typo'd axis only explodes at trace time, on hardware.
DL004  untraced Python side effects (print/time.time/ledger emits) inside
       jit/pjit/shard_map-traced functions — they fire once at trace time,
       then never again, which is a lie in a log.
DL005  PRNG hygiene: a key consumed twice (correlated draws), and global
       numpy/stdlib RNG state (per-process divergence, irreproducibility).
DL006  every ``*ledger*.emit(...)`` call site conforms to EVENT_SCHEMA
       (the absorbed tools/check_ledger_schema check).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.distlint.core import (FileContext, Finding, Project, dotted_name,
                                 terminal_name)


class Rule:
    id = "DL999"
    title = ""
    rationale = ""

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str) -> Finding:
        return Finding(self.id, ctx.rel, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), message)


def _calls(node: ast.AST) -> Iterable[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def _calls_same_scope(node: ast.AST) -> Iterable[ast.Call]:
    """Calls that EXECUTE when ``node`` executes: nested function/lambda
    bodies are pruned (they run at call time, not definition time)."""
    stack = list(ast.iter_child_nodes(node))
    if isinstance(node, ast.Call):
        yield node
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _block_exits(stmts: Sequence[ast.stmt]) -> bool:
    """Does this block unconditionally leave the enclosing code path?"""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


# ------------------------------------------------------------------ DL001
class HostDivergentCollectives(Rule):
    id = "DL001"
    title = "collective under host-divergent guard"
    rationale = ("a collective (or collective-entering call like "
                 "save_checkpoint/assemble_global) that only a subset of "
                 "processes reaches deadlocks the pod: the others wait in "
                 "the next collective forever")

    # call names that enter a cross-process collective (directly or, like
    # save_checkpoint's sharded gather, conditionally inside)
    COLLECTIVES = {
        "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
        "ppermute", "pshuffle", "axis_index",
        "process_allgather", "sync_global_devices", "broadcast_one_to_all",
        "assemble_global", "make_array_from_process_local_data",
        "save_checkpoint", "barrier", "allreduce", "adasum_reduce",
    }
    _DIVERGENT_NAMES = {"is_main", "is_master", "is_primary", "main_process"}

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        out: List[Finding] = []
        self._scan(ctx.tree.body, False, ctx, out)
        return out

    def _divergent(self, test: ast.AST) -> bool:
        for n in ast.walk(test):
            if (isinstance(n, ast.Call)
                    and terminal_name(n.func) == "process_index"):
                return True
            if (isinstance(n, (ast.Name, ast.Attribute))
                    and terminal_name(n) in self._DIVERGENT_NAMES):
                return True
            if isinstance(n, ast.Compare):
                # bare `rank` names only: `t.rank == 2` is a tensor-rank
                # check, identical on every host, not a process guard
                bare = {x.id for x in ast.walk(n) if isinstance(x, ast.Name)}
                attrs = {terminal_name(x) for x in ast.walk(n)
                         if isinstance(x, ast.Attribute)}
                if "rank" in bare or "process_index" in bare | attrs:
                    return True
        return False

    def _flag_collectives(self, node: ast.AST, ctx: FileContext,
                          out: List[Finding], how: str) -> None:
        # same-scope only: a function merely DEFINED under the guard may be
        # called on every host — flagging its body would be a false alarm
        for call in _calls_same_scope(node):
            name = terminal_name(call.func)
            if name in self.COLLECTIVES:
                out.append(self.finding(
                    ctx, call,
                    f"collective call '{name}' is reachable only on a "
                    f"subset of processes ({how}); the excluded hosts "
                    "never enter it and the pod deadlocks at the next "
                    "collective"))

    def _scan(self, stmts: Sequence[ast.stmt], active: bool,
              ctx: FileContext, out: List[Finding]) -> bool:
        """Linear pass with an 'active' flag: after an early return taken
        only on some processes, the REST of the block is host-divergent."""
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                # new runtime scope: divergence does not leak into a body
                # that executes at call time, not definition time
                body = s.body
                self._scan(body, False, ctx, out)
                continue
            if active:
                self._flag_collectives(s, ctx, out,
                                       "code after a process_index-guarded "
                                       "early return")
                continue
            if isinstance(s, ast.If) and self._divergent(s.test):
                self._flag_collectives(
                    s, ctx, out, "inside a process_index/is_main guard")
                # 'if not main: return' makes everything AFTER main-only;
                # symmetric for a guarded else-branch exit
                if _block_exits(s.body) or (s.orelse
                                            and _block_exits(s.orelse)):
                    active = True
                continue
            # sub-blocks are scanned with the INCOMING flag (an If's orelse
            # must not inherit divergence its sibling body introduced), but
            # a guarded early return inside ANY of them makes the code
            # after this statement divergent — propagate by OR-ing after
            escaped = False
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(s, attr, None)
                if sub:
                    escaped = self._scan(sub, active, ctx, out) or escaped
            for h in getattr(s, "handlers", ()):
                escaped = self._scan(h.body, active, ctx, out) or escaped
            active = active or escaped
        return active


# ------------------------------------------------------------------ DL002
class HotLoopHostSync(Rule):
    id = "DL002"
    title = "blocking host sync in a hot step loop"
    rationale = ("each .item()/device_get/np.asarray inside the step loop "
                 "drains the async-dispatch queue, serializing host and "
                 "device — the exact failure the drain-boundary design "
                 "avoids")

    # functions whose loops are the engines' hot paths (the decode tick is
    # a lax.scan INSIDE jit — DL004's domain — so generate.py carries no
    # Python-level hot loop to list here)
    HOT_FUNC_RE = re.compile(
        r"^(train_epoch|_train_epoch_windowed|_fit_epochs|validate)$")
    BLOCKING_METHODS = {"item", "block_until_ready", "tolist"}
    BLOCKING_QUALS = {"jax.device_get", "device_get", "numpy.asarray",
                      "numpy.array", "jax.block_until_ready"}

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.FunctionDef)
                    and self.HOT_FUNC_RE.match(node.name)):
                for loop in self._loops(node):
                    for stmt in loop.body + loop.orelse:
                        self._scan_stmt(stmt, node.name, ctx, out)
        return out

    def _loops(self, fn: ast.FunctionDef):
        """For/While nodes in fn, NOT descending into nested functions
        (generators/closures run off the hot path — prefetch threads)."""
        stack: List[ast.AST] = list(fn.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, (ast.For, ast.While)):
                yield n
                continue  # inner loops are reached via the body scan
            stack.extend(ast.iter_child_nodes(n))

    def _scan_stmt(self, stmt: ast.stmt, fn_name: str, ctx: FileContext,
                   out: List[Finding]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # off-loop execution (prefetch thread / deferred)
        for child in ast.iter_child_nodes(stmt):
            self._scan_stmt(child, fn_name, ctx, out)
        if isinstance(stmt, ast.Call):
            n = stmt
            bad = None
            tname = terminal_name(n.func)
            qual = ctx.resolve(dotted_name(n.func))
            if isinstance(n.func, ast.Attribute) \
                    and tname in self.BLOCKING_METHODS:
                bad = f".{tname}()"
            elif qual in self.BLOCKING_QUALS:
                bad = qual
            elif (isinstance(n.func, ast.Name) and n.func.id in ("float", "int")
                  and n.args
                  and isinstance(n.args[0], (ast.Name, ast.Attribute))):
                # float(x)/int(x) on a bare name is the classic implicit
                # device->host sync; subscript/call args are usually reads
                # of an already-fetched dict and stay silent
                bad = f"{n.func.id}({dotted_name(n.args[0])})"
            if bad:
                out.append(self.finding(
                    ctx, n,
                    f"blocking host sync {bad!r} inside the hot loop of "
                    f"{fn_name}() stalls async dispatch; queue device "
                    "values and fetch them at a drain boundary instead"))


# ------------------------------------------------------------------ DL003
class UnknownMeshAxis(Rule):
    id = "DL003"
    title = "axis name not declared on the mesh"
    rationale = ("a typo'd PartitionSpec axis ('modle') passes every CPU "
                 "test and only explodes at trace time on the pod; the "
                 "declared axes in parallel/mesh.py are the authority")

    SPEC_CTORS = {"P", "PartitionSpec"}
    AXIS_ARG_CALLS = {"psum", "pmean", "pmax", "pmin", "all_gather",
                      "all_to_all", "ppermute", "axis_index", "pbroadcast"}

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        axes = project.mesh_axes
        if not axes:
            return []
        out: List[Finding] = []
        for call in _calls(ctx.tree):
            tname = terminal_name(call.func)
            if tname in self.SPEC_CTORS:
                for lit in self._axis_literals(list(call.args)
                                               + [k.value for k in
                                                  call.keywords]):
                    self._validate(lit, axes, ctx, out, "PartitionSpec")
            elif tname in self.AXIS_ARG_CALLS:
                # axis_index(axis_name) takes the axis FIRST; the psum
                # family takes (value, axis_name)
                pos = 0 if tname == "axis_index" else 1
                cands = list(call.args[pos:pos + 1]) + [
                    k.value for k in call.keywords
                    if k.arg in ("axis_name", "axis")]
                for lit in self._axis_literals(cands):
                    self._validate(lit, axes, ctx, out, f"{tname}()")
        return out

    def _axis_literals(self, nodes) -> Iterable[ast.Constant]:
        for n in nodes:
            if isinstance(n, (ast.Tuple, ast.List)):
                yield from self._axis_literals(n.elts)
            elif isinstance(n, ast.Constant) and isinstance(n.value, str):
                yield n

    def _validate(self, lit: ast.Constant, axes: Set[str], ctx: FileContext,
                  out: List[Finding], where: str) -> None:
        if lit.value not in axes:
            out.append(self.finding(
                ctx, lit,
                f"axis {lit.value!r} in {where} is not a mesh axis "
                f"declared in tpu_dist/parallel/mesh.py "
                f"({sorted(axes)}); a typo here fails only at trace "
                "time on hardware"))


# ------------------------------------------------------------------ DL004
class TracedSideEffect(Rule):
    id = "DL004"
    title = "untraced Python side effect in jitted code"
    rationale = ("print/time.time/ledger emits inside jit/shard_map bodies "
                 "run ONCE at trace time and never again — a stale lie in "
                 "the logs; use jax.debug.print/callback or hoist to the "
                 "host loop")

    SIDE_EFFECT_QUALS = {"time.time", "time.perf_counter", "time.monotonic",
                         "time.sleep", "builtins.print"}
    SIDE_EFFECT_NAMES = {"print", "input", "breakpoint"}

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        defs: Dict[str, List[ast.FunctionDef]] = {}
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.FunctionDef):
                defs.setdefault(n.name, []).append(n)
        traced: List[ast.FunctionDef] = []
        seen: Set[int] = set()

        def mark(name: str) -> None:
            for fn in defs.get(name, ()):
                if id(fn) not in seen:
                    seen.add(id(fn))
                    traced.append(fn)

        def mark_nested(name: str) -> None:
            """jit(factory(...)): the TRACED code is whatever the factory
            returns — its nested defs — while the factory's own body is
            host-side build code that runs once and may print/time freely."""
            for fn in defs.get(name, ()):
                for n in ast.walk(fn):
                    if isinstance(n, ast.FunctionDef) and n is not fn \
                            and id(n) not in seen:
                        seen.add(id(n))
                        traced.append(n)

        for fn_list in defs.values():
            for fn in fn_list:
                if any(self._is_tracer(d, ctx) for d in fn.decorator_list):
                    mark(fn.name)
        for call in _calls(ctx.tree):
            if not self._is_tracer_call(call, ctx) or not call.args:
                continue
            arg = call.args[0]
            if isinstance(arg, ast.Name):
                mark(arg.id)
            elif isinstance(arg, ast.Call):
                inner = arg
                if terminal_name(inner.func) == "partial" and inner.args \
                        and isinstance(inner.args[0], ast.Name):
                    mark(inner.args[0].id)       # jit(partial(f, ...))
                else:
                    # factory pattern: jit(make_step(...)) traces the
                    # function the factory RETURNS — its nested defs
                    mark_nested(terminal_name(inner.func))
        out: List[Finding] = []
        for fn in traced:
            self._scan(fn, ctx, out)
        return out

    def _is_tracer(self, node: ast.AST, ctx: FileContext) -> bool:
        """jit / pjit / *shard_map* as a name, attribute, partial(...) or
        configured-call decorator."""
        if isinstance(node, ast.Call):
            if terminal_name(node.func) == "partial":
                return any(self._is_tracer(a, ctx) for a in node.args[:1])
            return self._is_tracer(node.func, ctx)
        t = terminal_name(node)
        return t in ("jit", "pjit") or "shard_map" in t

    def _is_tracer_call(self, call: ast.Call, ctx: FileContext) -> bool:
        t = terminal_name(call.func)
        return t in ("jit", "pjit") or "shard_map" in t

    def _scan(self, fn: ast.FunctionDef, ctx: FileContext,
              out: List[Finding]) -> None:
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            qual = ctx.resolve(dotted_name(n.func))
            if "debug" in qual or "callback" in qual:
                continue  # jax.debug.print / io_callback: the traced-safe way
            tname = terminal_name(n.func)
            hit = None
            if isinstance(n.func, ast.Name) \
                    and n.func.id in self.SIDE_EFFECT_NAMES:
                hit = n.func.id
            elif qual in self.SIDE_EFFECT_QUALS:
                hit = qual
            elif tname == "emit" and _is_ledger_receiver(n.func):
                hit = f"{dotted_name(n.func)}()"
            if hit:
                out.append(self.finding(
                    ctx, n,
                    f"untraced side effect {hit!r} inside the traced "
                    f"function {fn.name}() runs once at trace time and "
                    "never per step; use jax.debug.print/io_callback or "
                    "hoist it to the host loop"))


# ------------------------------------------------------------------ DL005
class PrngHygiene(Rule):
    id = "DL005"
    title = "PRNG key reuse / global RNG state"
    rationale = ("a key consumed twice yields correlated draws (silently "
                 "wrong statistics); global numpy/stdlib RNG state "
                 "diverges across processes and kills reproducibility — "
                 "use seeded np.random.default_rng / jax.random.fold_in")

    CONSUMERS = {"split", "normal", "uniform", "randint", "bernoulli",
                 "categorical", "permutation", "choice", "bits", "gamma",
                 "beta", "gumbel", "exponential", "laplace", "poisson",
                 "truncated_normal", "rademacher", "orthogonal", "shuffle",
                 "randint_like", "loggamma", "dirichlet", "multivariate_normal"}
    NP_SAFE = {"default_rng", "RandomState", "Generator", "SeedSequence",
               "get_state", "set_state", "bit_generator"}
    STDLIB_SAFE = {"Random", "SystemRandom"}

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Call):
                self._check_global_rng(n, ctx, out)
        for n in ast.walk(ctx.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_key_reuse(n, ctx, out)
        return out

    # -- global RNG state ------------------------------------------------
    def _check_global_rng(self, call: ast.Call, ctx: FileContext,
                          out: List[Finding]) -> None:
        qual = ctx.resolve(dotted_name(call.func))
        parts = qual.split(".")
        if len(parts) >= 3 and parts[-3] == "numpy" and parts[-2] == "random":
            if parts[-1] not in self.NP_SAFE:
                out.append(self.finding(
                    ctx, call,
                    f"global numpy RNG call '{qual}' draws from hidden "
                    "per-process state (seeding races, host divergence); "
                    "use a seeded np.random.default_rng(seed) generator"))
        elif len(parts) == 2 and parts[0] == "random":
            # qual is RESOLVED through the import table, so `import random
            # as rnd; rnd.randint` and `from random import randint` both
            # land here; `from jax import random` resolves to jax.random.*
            # (3 parts) and never does
            if parts[-1] not in self.STDLIB_SAFE:
                out.append(self.finding(
                    ctx, call,
                    f"stdlib global RNG call '{qual}' is process-local "
                    "hidden state; use random.Random(seed) or jax.random"))

    # -- jax key reuse ---------------------------------------------------
    def _check_key_reuse(self, fn: ast.AST, ctx: FileContext,
                         out: List[Finding]) -> None:
        uses: Dict[str, List[Tuple[int, ast.Call, tuple]]] = {}
        assigns: Dict[str, List[int]] = {}
        branches: Dict[int, tuple] = {}
        scope_nodes: List[ast.AST] = []

        def walk(node: ast.AST, path: tuple) -> None:
            for child_name, value in ast.iter_fields(node):
                kids = value if isinstance(value, list) else [value]
                for kid in kids:
                    if not isinstance(kid, ast.AST):
                        continue
                    sub = path
                    if isinstance(node, (ast.If, ast.Try)) \
                            and child_name in ("body", "orelse", "handlers",
                                               "finalbody"):
                        sub = path + ((id(node), child_name),)
                    if isinstance(kid, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda)) \
                            and kid is not fn:
                        continue   # nested scopes analyzed on their own
                    branches[id(kid)] = sub
                    scope_nodes.append(kid)
                    walk(kid, sub)

        branches[id(fn)] = ()
        walk(fn, ())

        for n in scope_nodes:
            if isinstance(n, ast.Call):
                qual = ctx.resolve(dotted_name(n.func))
                parts = qual.split(".")
                is_jax_rng = (len(parts) >= 3 and parts[-2] == "random"
                              and parts[-3] not in ("numpy",)
                              and parts[-1] in self.CONSUMERS)
                if is_jax_rng and n.args \
                        and isinstance(n.args[0], ast.Name):
                    uses.setdefault(n.args[0].id, []).append(
                        (n.lineno, n, branches.get(id(n), ())))
            for tgt in self._assign_targets(n):
                lineno = getattr(n, "lineno", None) or getattr(
                    getattr(n, "optional_vars", None), "lineno", 0)
                assigns.setdefault(tgt, []).append(lineno)

        for name, consumptions in uses.items():
            consumptions.sort(key=lambda u: u[0])
            for (l1, _, b1), (l2, node2, b2) in zip(consumptions,
                                                    consumptions[1:]):
                if any(l1 <= a < l2 for a in assigns.get(name, ())):
                    continue   # rebound between the two uses (rng, sub = ...)
                if self._sibling_branches(b1, b2):
                    continue   # if/else arms: only one executes
                out.append(self.finding(
                    ctx, node2,
                    f"PRNG key '{name}' is consumed again (line {l1} "
                    f"already passed it to jax.random) without a "
                    "re-split; reusing a key yields correlated draws — "
                    "split/fold_in first"))

    @staticmethod
    def _assign_targets(n: ast.AST) -> Iterable[str]:
        targets: List[ast.AST] = []
        if isinstance(n, ast.Assign):
            targets = list(n.targets)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            targets = [n.target]
        elif isinstance(n, ast.For):
            targets = [n.target]
        elif isinstance(n, ast.NamedExpr):
            targets = [n.target]
        elif isinstance(n, ast.withitem) and n.optional_vars is not None:
            targets = [n.optional_vars]
        for t in targets:
            if isinstance(t, ast.Name):
                yield t.id
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    if isinstance(e, ast.Name):
                        yield e.id

    @staticmethod
    def _sibling_branches(b1: tuple, b2: tuple) -> bool:
        for (n1, lbl1), (n2, lbl2) in zip(b1, b2):
            if n1 != n2:
                return False
            if lbl1 != lbl2:
                return True
        return False


# ------------------------------------------------------------------ DL006
FORWARD_MARK = "ledger-schema: forward"


def _is_ledger_receiver(func: ast.AST) -> bool:
    """Receiver of ``.emit`` looks like a ledger ('led' included so the
    natural short name cannot dodge the checker)."""
    if not isinstance(func, ast.Attribute):
        return False
    name = terminal_name(func.value).lower()
    return "ledger" in name or name == "led"


def check_emit_calls(ctx: FileContext, schema: Dict[str, tuple],
                     rule_id: str = "DL006") -> List[Finding]:
    """Every ``*ledger*.emit(...)`` call site names a declared event as a
    literal and passes all its required fields as explicit keywords (the
    former tools/check_ledger_schema.py walk, verbatim semantics —
    including the ``# ledger-schema: forward`` escape for wrappers that
    re-expose emit()'s own signature)."""
    out: List[Finding] = []
    for node in _calls(ctx.tree):
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "emit"
                and _is_ledger_receiver(f)):
            continue
        if FORWARD_MARK in ctx.line_text(node.lineno):
            continue
        mk = lambda msg: Finding(rule_id, ctx.rel, node.lineno,
                                 node.col_offset, msg)
        if not node.args:
            out.append(mk("emit() without an event argument"))
            continue
        ev = node.args[0]
        if not (isinstance(ev, ast.Constant) and isinstance(ev.value, str)):
            out.append(mk("event name must be a literal string "
                          "(static checkability)"))
            continue
        required = schema.get(ev.value)
        if required is None:
            out.append(mk(f"undeclared event {ev.value!r} "
                          f"(EVENT_SCHEMA: {sorted(schema)})"))
            continue
        kw = {k.arg for k in node.keywords if k.arg is not None}
        missing = [x for x in required if x not in kw]
        if missing:
            out.append(mk(f"event {ev.value!r} missing required "
                          f"keyword(s) {missing}"))
    return out


class LedgerSchema(Rule):
    id = "DL006"
    title = "ledger emit() schema conformance"
    rationale = ("schema drift — a renamed field, an undeclared event — "
                 "must fail at review time, not at 3am when someone greps "
                 "a ledger")

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        schema = project.event_schema
        if not schema:
            return []
        return check_emit_calls(ctx, schema, self.id)


RULES: List[Rule] = [HostDivergentCollectives(), HotLoopHostSync(),
                     UnknownMeshAxis(), TracedSideEffect(), PrngHygiene(),
                     LedgerSchema()]

RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in RULES}
