"""The distlint rule set: SPMD-correctness hazards visible in source.

Every rule is a pure function of (FileContext, Project) returning
:class:`~tools.distlint.core.Finding` objects. The hazards are the failure
classes the PR 2 watchdog can only report AFTER they hang a pod at runtime;
GSPMD single-program multi-host JAX makes them statically visible:

DL001  collectives/checkpoints reachable only under host-divergent guards
       (``process_index() == 0``-style) — the other hosts never enter the
       collective and the pod deadlocks.
DL002  blocking host syncs inside the engines' hot step loops — each one
       drains the async-dispatch queue and serializes the device.
DL003  axis-name literals in PartitionSpec/collective calls validated
       against the mesh axes declared in tpu_dist/parallel/mesh.py —
       a typo'd axis only explodes at trace time, on hardware.
DL004  untraced Python side effects (print/time.time/ledger emits) inside
       jit/pjit/shard_map-traced functions — they fire once at trace time,
       then never again, which is a lie in a log.
DL005  PRNG hygiene: a key consumed twice (correlated draws), and global
       numpy/stdlib RNG state (per-process divergence, irreproducibility).
DL006  every ``*ledger*.emit(...)`` call site conforms to EVENT_SCHEMA
       (the absorbed tools/check_ledger_schema check).
DL007  buffers donated to a jitted call (``donate_argnums``) referenced
       afterwards — the device buffer may already be reused by XLA.
DL008  bare ``jax.device_put`` on the hot step path outside the loader /
       prefetcher — the copy dispatch belongs on the producer thread
       (data.loader.DevicePrefetcher), not the step loop.  [warn tier]

The DL1xx family rides the cross-file call graph + reachability pass
(core.CallGraph): concurrency and signal-safety hazards in the threaded
obs layer, the failure class PR 5's Ledger SIGTERM deadlock proved real:

DL101  non-reentrant ``threading.Lock`` acquired on a path reachable from
       a signal handler while the same lock guards main-thread emit
       sites (the exact PR-5 self-deadlock; the shipped RLock is clean).
DL102  blocking I/O (subprocess/socket/HTTP/sleep) while holding a lock
       the hot-path emit fan-out also takes.  [warn tier]
DL103  ``threading.Thread`` without ``daemon=True`` and without a join on
       the shutdown path — a crashed run that never exits.  [warn tier]
DL104  signal handlers calling non-reentrant stdlib (logging, io flush
       chains), and ``signal.signal`` installs that drop the previously
       installed handler instead of chaining it.

Severity tiers: every rule carries ``severity`` ('error' gates CI via
scripts/lint.sh; 'warn' reports without failing the build).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.distlint.core import (FileContext, Finding, Project, dotted_name,
                                 graph_scope, terminal_name)


class Rule:
    id = "DL999"
    title = ""
    rationale = ""
    # severity tier: 'error' findings gate CI (scripts/lint.sh exits
    # non-zero); 'warn' findings report but do not fail the build — the
    # tier for heuristic-leaning rules whose false-positive cost is real
    severity = "error"
    # graph-backed rules open graph_scope; lint_files hoists ONE
    # ensure/remove of the file per lint pass when any is selected, so
    # five rules don't re-index (and re-invalidate the reachability
    # memos of) an out-of-surface file five times
    uses_graph = False

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str) -> Finding:
        return Finding(self.id, ctx.rel, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), message)


def _assign_parts(stmt: ast.AST) -> Tuple[Optional[ast.AST],
                                          Optional[ast.AST]]:
    """(target, value) for plain and annotated single-target assigns."""
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
        return stmt.targets[0], stmt.value
    if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        return stmt.target, stmt.value
    return None, None


def _calls(node: ast.AST) -> Iterable[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def _calls_same_scope(node: ast.AST) -> Iterable[ast.Call]:
    """Calls that EXECUTE when ``node`` executes: nested function/lambda
    bodies are pruned (they run at call time, not definition time)."""
    stack = list(ast.iter_child_nodes(node))
    if isinstance(node, ast.Call):
        yield node
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _block_exits(stmts: Sequence[ast.stmt]) -> bool:
    """Does this block unconditionally leave the enclosing code path?"""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


# ------------------------------------------------------------------ DL001
class HostDivergentCollectives(Rule):
    id = "DL001"
    title = "collective under host-divergent guard"
    rationale = ("a collective (or collective-entering call like "
                 "save_checkpoint/assemble_global) that only a subset of "
                 "processes reaches deadlocks the pod: the others wait in "
                 "the next collective forever")

    # call names that enter a cross-process collective (directly or, like
    # save_checkpoint's sharded gather, conditionally inside)
    COLLECTIVES = {
        "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
        "ppermute", "pshuffle", "axis_index", "psum_scatter",
        "process_allgather", "sync_global_devices", "broadcast_one_to_all",
        "assemble_global", "make_array_from_process_local_data",
        "save_checkpoint", "barrier", "allreduce", "adasum_reduce",
        # the ring/decode collectives (parallel/overlap.py,
        # parallel/collectives.py): ppermute/psum_scatter chains under the
        # hood, so a host-divergent guard around them deadlocks identically
        "ring_allreduce", "ring_allgather_matmul",
        "ring_matmul_reduce_scatter", "bucketed_grad_sync", "reduce_mean",
    }
    _DIVERGENT_NAMES = {"is_main", "is_master", "is_primary", "main_process"}
    _GATE_RE = re.compile(r"process_index|is_main|is_master|is_primary|"
                          r"main_process|rank")

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        if not self._GATE_RE.search(ctx.src):
            return []   # no divergence vocabulary: no guard to flag
        out: List[Finding] = []
        self._scan(ctx.tree.body, False, ctx, out)
        return out

    def _divergent(self, test: ast.AST) -> bool:
        for n in ast.walk(test):
            if (isinstance(n, ast.Call)
                    and terminal_name(n.func) == "process_index"):
                return True
            if (isinstance(n, (ast.Name, ast.Attribute))
                    and terminal_name(n) in self._DIVERGENT_NAMES):
                return True
            if isinstance(n, ast.Compare):
                # bare `rank` names only: `t.rank == 2` is a tensor-rank
                # check, identical on every host, not a process guard
                bare = {x.id for x in ast.walk(n) if isinstance(x, ast.Name)}
                attrs = {terminal_name(x) for x in ast.walk(n)
                         if isinstance(x, ast.Attribute)}
                if "rank" in bare or "process_index" in bare | attrs:
                    return True
        return False

    def _flag_collectives(self, node: ast.AST, ctx: FileContext,
                          out: List[Finding], how: str) -> None:
        # same-scope only: a function merely DEFINED under the guard may be
        # called on every host — flagging its body would be a false alarm
        for call in _calls_same_scope(node):
            name = terminal_name(call.func)
            if name in self.COLLECTIVES:
                out.append(self.finding(
                    ctx, call,
                    f"collective call '{name}' is reachable only on a "
                    f"subset of processes ({how}); the excluded hosts "
                    "never enter it and the pod deadlocks at the next "
                    "collective"))

    def _scan(self, stmts: Sequence[ast.stmt], active: bool,
              ctx: FileContext, out: List[Finding]) -> bool:
        """Linear pass with an 'active' flag: after an early return taken
        only on some processes, the REST of the block is host-divergent."""
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                # new runtime scope: divergence does not leak into a body
                # that executes at call time, not definition time
                body = s.body
                self._scan(body, False, ctx, out)
                continue
            if active:
                self._flag_collectives(s, ctx, out,
                                       "code after a process_index-guarded "
                                       "early return")
                continue
            if isinstance(s, ast.If) and self._divergent(s.test):
                self._flag_collectives(
                    s, ctx, out, "inside a process_index/is_main guard")
                # 'if not main: return' makes everything AFTER main-only;
                # symmetric for a guarded else-branch exit
                if _block_exits(s.body) or (s.orelse
                                            and _block_exits(s.orelse)):
                    active = True
                continue
            # sub-blocks are scanned with the INCOMING flag (an If's orelse
            # must not inherit divergence its sibling body introduced), but
            # a guarded early return inside ANY of them makes the code
            # after this statement divergent — propagate by OR-ing after
            escaped = False
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(s, attr, None)
                if sub:
                    escaped = self._scan(sub, active, ctx, out) or escaped
            for h in getattr(s, "handlers", ()):
                escaped = self._scan(h.body, active, ctx, out) or escaped
            active = active or escaped
        return active


# ------------------------------------------------------------------ DL002
class HotLoopHostSync(Rule):
    uses_graph = True
    id = "DL002"
    title = "blocking host sync on the hot step path"
    rationale = ("each .item()/device_get/np.asarray inside the step loop "
                 "drains the async-dispatch queue, serializing host and "
                 "device — the exact failure the drain-boundary design "
                 "avoids")

    # What counts as hot is DERIVED, not listed: a loop is a step loop
    # when its body (transitively, through the call graph) dispatches a
    # jit/shard_map-traced computation — either a resolved traced handle
    # (self.train_step = make_train_step(...) where the maker returns
    # jax.jit(...)) or, as a syntactic backstop, a callee whose name says
    # it dispatches steps. Everything REACHABLE from a step-loop body is
    # hot too, which closes the old closure seam: a .item() inside a
    # helper or nested def that the loop calls no longer escapes because
    # the def's body sat outside the loop's lexical extent.
    STEP_NAME_RE = re.compile(r"step|dispatch", re.I)
    BLOCKING_METHODS = {"item", "block_until_ready", "tolist"}
    BLOCKING_QUALS = {"jax.device_get", "device_get", "numpy.asarray",
                      "numpy.array", "jax.block_until_ready"}
    # the reachable-body scan (helpers called FROM a hot loop) narrows
    # only the QUALS: np.asarray/float(x) on host values is ordinary
    # Python in a constructor or parser, and flagging it there would
    # bury the real syncs in noise — lexically inside a step loop the
    # odds flip, so the full qual set applies only there. The method
    # set (.item()/.tolist()/.block_until_ready()) is unambiguous in
    # either position and applies to both tiers.
    STRICT_QUALS = {"jax.device_get", "device_get",
                    "jax.block_until_ready"}

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        out: List[Finding] = []
        with graph_scope(project, ctx) as g:
            reaches = g.reaches_traced()
            traced = g.traced_funcs()
            hot = self._hot_funcs(g, reaches, traced)
            for node in g.file_nodes(ctx.rel):
                if node.qual in traced:
                    continue
                # lexical: statements inside this file's hot loop bodies —
                # the `<module>` pseudo-node included (a top-level step
                # loop in a script is as hot as one in a function; only
                # the hot-BODY rescan below needs a real def node).
                # Loops with GRAPH EVIDENCE of a traced dispatch get the
                # full blocking set (float(x)/np.asarray included); loops
                # hot only by callee NAME get the strict set — a drain
                # loop iterating already-fetched host floats must not
                # drown the report in int(host_value) noise
                for loop in node.loops:
                    how = self._loop_is_hot(node, loop, g, reaches, traced)
                    if how:
                        for stmt in loop.body + loop.orelse:
                            self._scan_stmt(stmt, node.name, ctx, out,
                                            strict=(how == 1),
                                            lexical=True)
                # reachability: whole body of functions called (directly
                # or transitively) from ANY hot loop body in the project
                if node.node is not None and node.qual in hot:
                    for stmt in node.node.body:
                        self._scan_stmt(stmt, node.name, ctx, out,
                                        strict=True, lexical=False)
        seen: set = set()
        uniq: List[Finding] = []
        for f in sorted(out, key=lambda f: (f.line, f.col)):
            if (f.line, f.col) not in seen:
                seen.add((f.line, f.col))
                uniq.append(f)
        return uniq

    def _loop_calls(self, node, loop) -> List[str]:
        """Same-scope call heads whose call site sits inside ``loop``'s
        body (the node's call list excludes nested-def bodies already)."""
        end = getattr(loop, "end_lineno", loop.lineno)
        return [h for h, line in node.calls if loop.lineno <= line <= end]

    def _loop_is_hot(self, node, loop, g, reaches, traced) -> int:
        """0 = not hot; 2 = hot with graph evidence (a body call resolves
        to a traced computation); 1 = hot by callee name only."""
        how = 0
        for head in self._loop_calls(node, loop):
            targets, is_traced = g.resolve(node, head)
            if is_traced or any(t in reaches or t in traced
                                for t in targets):
                return 2
            if self.STEP_NAME_RE.search(head.rpartition(".")[2]):
                how = 1
        return how

    def _hot_funcs(self, g, reaches, traced) -> set:
        """Functions reachable from any hot loop body in the graph (the
        project surface plus the file under lint), minus traced bodies —
        memoized on the graph version."""
        def compute():
            roots: List[str] = []
            for node in g.funcs.values():
                if node.qual in traced:
                    continue   # module nodes seed too: top-level loops
                for loop in node.loops:
                    if self._loop_is_hot(node, loop, g, reaches, traced):
                        for head in self._loop_calls(node, loop):
                            targets, _ = g.resolve(node, head)
                            roots.extend(t for t in targets
                                         if t not in traced)
            return g.reachable_from(roots) - traced
        return g._memoized("dl002_hot", compute)

    def _scan_stmt(self, stmt: ast.stmt, fn_name: str, ctx: FileContext,
                   out: List[Finding], strict: bool = False,
                   lexical: bool = True) -> None:
        # `strict` narrows the blocking-qual set; `lexical` picks the
        # message (inside this loop vs reachable from one) — independent
        # axes: a name-only hot loop is strict AND lexical
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # separate node: the reachability pass covers it
        for child in ast.iter_child_nodes(stmt):
            self._scan_stmt(child, fn_name, ctx, out, strict, lexical)
        if isinstance(stmt, ast.Call):
            n = stmt
            bad = None
            tname = terminal_name(n.func)
            qual = ctx.resolve(dotted_name(n.func))
            methods = self.BLOCKING_METHODS   # same set in both tiers
            quals = self.STRICT_QUALS if strict else self.BLOCKING_QUALS
            if isinstance(n.func, ast.Attribute) and tname in methods:
                bad = f".{tname}()"
            elif qual in quals:
                bad = qual
            elif (not strict and isinstance(n.func, ast.Name)
                  and n.func.id in ("float", "int") and n.args
                  and isinstance(n.args[0], (ast.Name, ast.Attribute))):
                # float(x)/int(x) on a bare name is the classic implicit
                # device->host sync; subscript/call args are usually reads
                # of an already-fetched dict and stay silent
                bad = f"{n.func.id}({dotted_name(n.args[0])})"
            if bad:
                where = (f"inside the hot loop of {fn_name}()" if lexical
                         else f"in {fn_name}(), reachable from a hot "
                              f"step loop")
                out.append(self.finding(
                    ctx, n,
                    f"blocking host sync {bad!r} {where} stalls async "
                    "dispatch; queue device values and fetch them at a "
                    "drain boundary instead"))


# ------------------------------------------------------------------ DL003
class UnknownMeshAxis(Rule):
    id = "DL003"
    title = "axis name not declared on the mesh"
    rationale = ("a typo'd PartitionSpec axis ('modle') passes every CPU "
                 "test and only explodes at trace time on the pod; the "
                 "declared axes in parallel/mesh.py are the authority")

    SPEC_CTORS = {"P", "PartitionSpec"}
    AXIS_ARG_CALLS = {"psum", "pmean", "pmax", "pmin", "all_gather",
                      "all_to_all", "ppermute", "axis_index", "pbroadcast",
                      "psum_scatter", "axis_size"}

    _GATE_RE = re.compile(r"PartitionSpec|P\(|psum|pmean|pmax|pmin|"
                          r"all_gather|all_to_all|ppermute|axis_index|"
                          r"pbroadcast|axis_size|\.shape\[")

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        axes = project.mesh_axes
        if not axes or not self._GATE_RE.search(ctx.src):
            return []
        out: List[Finding] = []
        for call in _calls(ctx.tree):
            tname = terminal_name(call.func)
            if tname in self.SPEC_CTORS:
                for lit in self._axis_literals(list(call.args)
                                               + [k.value for k in
                                                  call.keywords]):
                    self._validate(lit, axes, ctx, out, "PartitionSpec")
            elif tname in self.AXIS_ARG_CALLS:
                # axis_index/axis_size(axis_name) take the axis FIRST;
                # the psum family takes (value, axis_name)
                pos = 0 if tname in ("axis_index", "axis_size") else 1
                cands = list(call.args[pos:pos + 1]) + [
                    k.value for k in call.keywords
                    if k.arg in ("axis_name", "axis")]
                for lit in self._axis_literals(cands):
                    self._validate(lit, axes, ctx, out, f"{tname}()")
        # mesh.shape["axis"] — Mesh.shape is keyed by axis NAME; a typo'd
        # key raises KeyError only when the serving path first sizes the
        # axis on hardware (array .shape subscripts are ints, never str)
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Attribute)
                    and node.value.attr == "shape"
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                self._validate(node.slice, axes, ctx, out,
                               f"{dotted_name(node.value)}[...]")
        return out

    def _axis_literals(self, nodes) -> Iterable[ast.Constant]:
        for n in nodes:
            if isinstance(n, (ast.Tuple, ast.List)):
                yield from self._axis_literals(n.elts)
            elif isinstance(n, ast.Constant) and isinstance(n.value, str):
                yield n

    def _validate(self, lit: ast.Constant, axes: Set[str], ctx: FileContext,
                  out: List[Finding], where: str) -> None:
        if lit.value not in axes:
            out.append(self.finding(
                ctx, lit,
                f"axis {lit.value!r} in {where} is not a mesh axis "
                f"declared in tpu_dist/parallel/mesh.py "
                f"({sorted(axes)}); a typo here fails only at trace "
                "time on hardware"))


# ------------------------------------------------------------------ DL004
class TracedSideEffect(Rule):
    id = "DL004"
    title = "untraced Python side effect in jitted code"
    rationale = ("print/time.time/ledger emits inside jit/shard_map bodies "
                 "run ONCE at trace time and never again — a stale lie in "
                 "the logs; use jax.debug.print/callback or hoist to the "
                 "host loop")

    SIDE_EFFECT_QUALS = {"time.time", "time.perf_counter", "time.monotonic",
                         "time.sleep", "builtins.print"}
    SIDE_EFFECT_NAMES = {"print", "input", "breakpoint"}

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        if "jit" not in ctx.src and "shard_map" not in ctx.src:
            return []   # nothing traced here
        defs: Dict[str, List[ast.FunctionDef]] = {}
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.FunctionDef):
                defs.setdefault(n.name, []).append(n)
        traced: List[ast.FunctionDef] = []
        seen: Set[int] = set()

        def mark(name: str) -> None:
            for fn in defs.get(name, ()):
                if id(fn) not in seen:
                    seen.add(id(fn))
                    traced.append(fn)

        def mark_nested(name: str) -> None:
            """jit(factory(...)): the TRACED code is whatever the factory
            returns — its nested defs — while the factory's own body is
            host-side build code that runs once and may print/time freely."""
            for fn in defs.get(name, ()):
                for n in ast.walk(fn):
                    if isinstance(n, ast.FunctionDef) and n is not fn \
                            and id(n) not in seen:
                        seen.add(id(n))
                        traced.append(n)

        for fn_list in defs.values():
            for fn in fn_list:
                if any(self._is_tracer(d, ctx) for d in fn.decorator_list):
                    mark(fn.name)
        for call in _calls(ctx.tree):
            if not self._is_tracer_call(call, ctx) or not call.args:
                continue
            arg = call.args[0]
            if isinstance(arg, ast.Name):
                mark(arg.id)
            elif isinstance(arg, ast.Call):
                inner = arg
                if terminal_name(inner.func) == "partial" and inner.args \
                        and isinstance(inner.args[0], ast.Name):
                    mark(inner.args[0].id)       # jit(partial(f, ...))
                else:
                    # factory pattern: jit(make_step(...)) traces the
                    # function the factory RETURNS — its nested defs
                    mark_nested(terminal_name(inner.func))
        out: List[Finding] = []
        for fn in traced:
            self._scan(fn, ctx, out)
        return out

    def _is_tracer(self, node: ast.AST, ctx: FileContext) -> bool:
        """jit / pjit / *shard_map* as a name, attribute, partial(...) or
        configured-call decorator."""
        if isinstance(node, ast.Call):
            if terminal_name(node.func) == "partial":
                return any(self._is_tracer(a, ctx) for a in node.args[:1])
            return self._is_tracer(node.func, ctx)
        t = terminal_name(node)
        return t in ("jit", "pjit") or "shard_map" in t

    def _is_tracer_call(self, call: ast.Call, ctx: FileContext) -> bool:
        t = terminal_name(call.func)
        return t in ("jit", "pjit") or "shard_map" in t

    def _scan(self, fn: ast.FunctionDef, ctx: FileContext,
              out: List[Finding]) -> None:
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            qual = ctx.resolve(dotted_name(n.func))
            if "debug" in qual or "callback" in qual:
                continue  # jax.debug.print / io_callback: the traced-safe way
            tname = terminal_name(n.func)
            hit = None
            if isinstance(n.func, ast.Name) \
                    and n.func.id in self.SIDE_EFFECT_NAMES:
                hit = n.func.id
            elif qual in self.SIDE_EFFECT_QUALS:
                hit = qual
            elif tname == "emit" and _is_ledger_receiver(n.func):
                hit = f"{dotted_name(n.func)}()"
            if hit:
                out.append(self.finding(
                    ctx, n,
                    f"untraced side effect {hit!r} inside the traced "
                    f"function {fn.name}() runs once at trace time and "
                    "never per step; use jax.debug.print/io_callback or "
                    "hoist it to the host loop"))


# ------------------------------------------------------------------ DL005
class PrngHygiene(Rule):
    id = "DL005"
    title = "PRNG key reuse / global RNG state"
    rationale = ("a key consumed twice yields correlated draws (silently "
                 "wrong statistics); global numpy/stdlib RNG state "
                 "diverges across processes and kills reproducibility — "
                 "use seeded np.random.default_rng / jax.random.fold_in")

    CONSUMERS = {"split", "normal", "uniform", "randint", "bernoulli",
                 "categorical", "permutation", "choice", "bits", "gamma",
                 "beta", "gumbel", "exponential", "laplace", "poisson",
                 "truncated_normal", "rademacher", "orthogonal", "shuffle",
                 "randint_like", "loggamma", "dirichlet", "multivariate_normal"}
    NP_SAFE = {"default_rng", "RandomState", "Generator", "SeedSequence",
               "get_state", "set_state", "bit_generator"}
    STDLIB_SAFE = {"Random", "SystemRandom"}

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        if "random" not in ctx.src:
            return []   # both halves of the rule need RNG vocabulary
        out: List[Finding] = []
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Call):
                self._check_global_rng(n, ctx, out)
        for n in ast.walk(ctx.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_key_reuse(n, ctx, out)
        return out

    # -- global RNG state ------------------------------------------------
    def _check_global_rng(self, call: ast.Call, ctx: FileContext,
                          out: List[Finding]) -> None:
        qual = ctx.resolve(dotted_name(call.func))
        parts = qual.split(".")
        if len(parts) >= 3 and parts[-3] == "numpy" and parts[-2] == "random":
            if parts[-1] not in self.NP_SAFE:
                out.append(self.finding(
                    ctx, call,
                    f"global numpy RNG call '{qual}' draws from hidden "
                    "per-process state (seeding races, host divergence); "
                    "use a seeded np.random.default_rng(seed) generator"))
        elif len(parts) == 2 and parts[0] == "random":
            # qual is RESOLVED through the import table, so `import random
            # as rnd; rnd.randint` and `from random import randint` both
            # land here; `from jax import random` resolves to jax.random.*
            # (3 parts) and never does
            if parts[-1] not in self.STDLIB_SAFE:
                out.append(self.finding(
                    ctx, call,
                    f"stdlib global RNG call '{qual}' is process-local "
                    "hidden state; use random.Random(seed) or jax.random"))

    # -- jax key reuse ---------------------------------------------------
    def _check_key_reuse(self, fn: ast.AST, ctx: FileContext,
                         out: List[Finding]) -> None:
        uses: Dict[str, List[Tuple[int, ast.Call, tuple]]] = {}
        assigns: Dict[str, List[int]] = {}
        branches: Dict[int, tuple] = {}
        scope_nodes: List[ast.AST] = []

        def walk(node: ast.AST, path: tuple) -> None:
            for child_name, value in ast.iter_fields(node):
                kids = value if isinstance(value, list) else [value]
                for kid in kids:
                    if not isinstance(kid, ast.AST):
                        continue
                    sub = path
                    if isinstance(node, (ast.If, ast.Try)) \
                            and child_name in ("body", "orelse", "handlers",
                                               "finalbody"):
                        sub = path + ((id(node), child_name),)
                    if isinstance(kid, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda)) \
                            and kid is not fn:
                        continue   # nested scopes analyzed on their own
                    branches[id(kid)] = sub
                    scope_nodes.append(kid)
                    walk(kid, sub)

        branches[id(fn)] = ()
        walk(fn, ())

        for n in scope_nodes:
            if isinstance(n, ast.Call):
                qual = ctx.resolve(dotted_name(n.func))
                parts = qual.split(".")
                is_jax_rng = (len(parts) >= 3 and parts[-2] == "random"
                              and parts[-3] not in ("numpy",)
                              and parts[-1] in self.CONSUMERS)
                if is_jax_rng and n.args \
                        and isinstance(n.args[0], ast.Name):
                    uses.setdefault(n.args[0].id, []).append(
                        (n.lineno, n, branches.get(id(n), ())))
            for tgt in self._assign_targets(n):
                lineno = getattr(n, "lineno", None) or getattr(
                    getattr(n, "optional_vars", None), "lineno", 0)
                assigns.setdefault(tgt, []).append(lineno)

        for name, consumptions in uses.items():
            consumptions.sort(key=lambda u: u[0])
            for (l1, _, b1), (l2, node2, b2) in zip(consumptions,
                                                    consumptions[1:]):
                if any(l1 <= a < l2 for a in assigns.get(name, ())):
                    continue   # rebound between the two uses (rng, sub = ...)
                if self._sibling_branches(b1, b2):
                    continue   # if/else arms: only one executes
                out.append(self.finding(
                    ctx, node2,
                    f"PRNG key '{name}' is consumed again (line {l1} "
                    f"already passed it to jax.random) without a "
                    "re-split; reusing a key yields correlated draws — "
                    "split/fold_in first"))

    @staticmethod
    def _assign_targets(n: ast.AST) -> Iterable[str]:
        targets: List[ast.AST] = []
        if isinstance(n, ast.Assign):
            targets = list(n.targets)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            targets = [n.target]
        elif isinstance(n, ast.For):
            targets = [n.target]
        elif isinstance(n, ast.NamedExpr):
            targets = [n.target]
        elif isinstance(n, ast.withitem) and n.optional_vars is not None:
            targets = [n.optional_vars]
        for t in targets:
            if isinstance(t, ast.Name):
                yield t.id
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    if isinstance(e, ast.Name):
                        yield e.id

    @staticmethod
    def _sibling_branches(b1: tuple, b2: tuple) -> bool:
        for (n1, lbl1), (n2, lbl2) in zip(b1, b2):
            if n1 != n2:
                return False
            if lbl1 != lbl2:
                return True
        return False


# ------------------------------------------------------------------ DL006
FORWARD_MARK = "ledger-schema: forward"


def _is_ledger_receiver(func: ast.AST) -> bool:
    """Receiver of ``.emit`` looks like a ledger ('led' included so the
    natural short name cannot dodge the checker)."""
    if not isinstance(func, ast.Attribute):
        return False
    name = terminal_name(func.value).lower()
    return "ledger" in name or name == "led"


def check_emit_calls(ctx: FileContext, schema: Dict[str, tuple],
                     rule_id: str = "DL006") -> List[Finding]:
    """Every ``*ledger*.emit(...)`` call site names a declared event as a
    literal and passes all its required fields as explicit keywords (the
    former tools/check_ledger_schema.py walk, verbatim semantics —
    including the ``# ledger-schema: forward`` escape for wrappers that
    re-expose emit()'s own signature)."""
    out: List[Finding] = []
    for node in _calls(ctx.tree):
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "emit"
                and _is_ledger_receiver(f)):
            continue
        if FORWARD_MARK in ctx.line_text(node.lineno):
            continue
        mk = lambda msg: Finding(rule_id, ctx.rel, node.lineno,
                                 node.col_offset, msg)
        if not node.args:
            out.append(mk("emit() without an event argument"))
            continue
        ev = node.args[0]
        if not (isinstance(ev, ast.Constant) and isinstance(ev.value, str)):
            out.append(mk("event name must be a literal string "
                          "(static checkability)"))
            continue
        required = schema.get(ev.value)
        if required is None:
            out.append(mk(f"undeclared event {ev.value!r} "
                          f"(EVENT_SCHEMA: {sorted(schema)})"))
            continue
        kw = {k.arg for k in node.keywords if k.arg is not None}
        missing = [x for x in required if x not in kw]
        if missing:
            out.append(mk(f"event {ev.value!r} missing required "
                          f"keyword(s) {missing}"))
    return out


class LedgerSchema(Rule):
    id = "DL006"
    title = "ledger emit() schema conformance"
    rationale = ("schema drift — a renamed field, an undeclared event — "
                 "must fail at review time, not at 3am when someone greps "
                 "a ledger")

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        if ".emit(" not in ctx.src:
            return []
        schema = project.event_schema
        if not schema:
            return []
        return check_emit_calls(ctx, schema, self.id)


# ------------------------------------------------------------------ DL007
class DonatedBufferReuse(Rule):
    id = "DL007"
    title = "donated buffer referenced after the jitted call"
    rationale = ("donate_argnums hands the argument's device buffer to "
                 "XLA for reuse; reading the Python reference afterwards "
                 "returns garbage (or raises on deletion-checking "
                 "backends) — rebind or stop donating")

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        if "donate_argnums" not in ctx.src:
            return []   # cheap text gate before any AST walking
        out: List[Finding] = []
        # module-level jit handles (`step = jax.jit(f, donate_argnums=..)`)
        # are visible to every function scope — collect them first
        module_donating: Dict[str, Tuple[int, ...]] = {}
        for stmt in ctx.tree.body:
            tgt, val = _assign_parts(stmt)
            if isinstance(tgt, ast.Name) and isinstance(val, ast.Call) \
                    and terminal_name(val.func) in ("jit", "pjit"):
                pos = self._donated_positions(val)
                if pos:
                    module_donating[tgt.id] = pos
        scopes = [ctx.tree] + [n for n in ast.walk(ctx.tree)
                               if isinstance(n, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef))]
        for scope in scopes:
            self._check_scope(scope, ctx, out, dict(module_donating))
        return out

    @staticmethod
    def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
        for k in call.keywords:
            if k.arg == "donate_argnums":
                v = k.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    pos = tuple(e.value for e in v.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, int))
                    return pos or None
        return None

    def _check_scope(self, scope, ctx: FileContext, out: List[Finding],
                     donating: Optional[Dict[str, Tuple[int, ...]]] = None
                     ) -> None:
        body = scope.body if hasattr(scope, "body") else []
        donating = dict(donating or {})
        # ordering is by (line, col) against the call's END position —
        # args on continuation lines of a multi-line call sit inside the
        # span (not "after" it), and a same-line read past the closing
        # paren (`return f(s), s.step`) is a real post-donation use
        consumed: List[Tuple[str, int, Tuple[int, int], ast.AST]] = []
        assigns: Dict[str, List[Tuple[int, int]]] = {}
        reads: Dict[str, List[Tuple[Tuple[int, int], ast.AST]]] = {}

        def walk(n: ast.AST) -> None:
            for kid in ast.iter_child_nodes(n):
                if isinstance(kid, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    continue   # nested scopes get their own pass
                walk(kid)
            tgt, val = _assign_parts(n)
            if isinstance(tgt, ast.Name) and isinstance(val, ast.Call) \
                    and terminal_name(val.func) in ("jit", "pjit"):
                pos = self._donated_positions(val)
                if pos:
                    donating[tgt.id] = pos
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id in donating:
                end = (n.end_lineno or n.lineno, n.end_col_offset or 0)
                for p in donating[n.func.id]:
                    if p < len(n.args) and isinstance(n.args[p], ast.Name):
                        consumed.append((n.args[p].id, n.lineno, end, n))
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Load):
                    reads.setdefault(n.id, []).append(
                        ((n.lineno, n.col_offset), n))
                else:
                    assigns.setdefault(n.id, []).append(
                        (n.lineno, n.col_offset))

        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            walk(stmt)
        for var, call_line, call_end, _ in consumed:
            rebinds = assigns.get(var, ())
            for read_pos, node in sorted(reads.get(var, ()),
                                         key=lambda r: r[0]):
                if read_pos <= call_end:
                    continue   # before the call, or one of its own args
                if any((call_line, -1) <= a < read_pos for a in rebinds):
                    continue   # state = step(state, ...) rebinding pattern
                out.append(self.finding(
                    ctx, node,
                    f"'{var}' was donated to a jitted call on line "
                    f"{call_line} (donate_argnums) and is read again here; "
                    "its device buffer may already be reused — rebind the "
                    "result or drop the donation"))
                break   # one finding per (var, donation) pair is enough
        return


# ------------------------------------------------------------------ DL008
class HotLoopDevicePut(Rule):
    uses_graph = True
    id = "DL008"
    title = "bare device_put on the hot step path"
    severity = "warn"
    rationale = ("a device_put dispatched from the step loop charges the "
                 "host->device copy to the consumer's critical path — the "
                 "data_s the round-9 DevicePrefetcher exists to hide; "
                 "stage uploads through data.loader (DevicePrefetcher / "
                 "prefetch_to_device) so the dispatch rides the producer "
                 "thread")

    # the loader IS the staging layer: its device_put/
    # make_array_from_process_local_data call sites are the one legitimate
    # home for hot-path uploads (every engine rides them via
    # prefetch_to_device / stream_prefetch)
    LOADER_FILES = {"tpu_dist/data/loader.py"}
    PUT_QUALS = {"jax.device_put", "device_put"}

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        if "device_put" not in ctx.src:
            return []   # cheap text gate before opening the graph
        if ctx.rel.replace("\\", "/") in self.LOADER_FILES:
            return []
        helper = RULES_BY_ID["DL002"]   # shares the derived hot set
        out: List[Finding] = []
        with graph_scope(project, ctx) as g:
            reaches = g.reaches_traced()
            traced = g.traced_funcs()
            hot = helper._hot_funcs(g, reaches, traced)
            for node in g.file_nodes(ctx.rel):
                if node.qual in traced:
                    continue
                for loop in node.loops:
                    if helper._loop_is_hot(node, loop, g, reaches, traced):
                        for stmt in loop.body + loop.orelse:
                            self._scan_stmt(stmt, node.name, ctx, out,
                                            lexical=True)
                if node.node is not None and node.qual in hot:
                    for stmt in node.node.body:
                        self._scan_stmt(stmt, node.name, ctx, out,
                                        lexical=False)
        seen: Set[Tuple[int, int]] = set()
        uniq: List[Finding] = []
        for f in sorted(out, key=lambda f: (f.line, f.col)):
            if (f.line, f.col) not in seen:
                seen.add((f.line, f.col))
                uniq.append(f)
        return uniq

    def _scan_stmt(self, stmt: ast.stmt, fn_name: str, ctx: FileContext,
                   out: List[Finding], lexical: bool) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # separate node: the reachability pass covers it
        for child in ast.iter_child_nodes(stmt):
            self._scan_stmt(child, fn_name, ctx, out, lexical)
        if isinstance(stmt, ast.Call) \
                and ctx.resolve(dotted_name(stmt.func)) in self.PUT_QUALS:
            where = (f"inside the hot loop of {fn_name}()" if lexical
                     else f"in {fn_name}(), reachable from a hot step loop")
            out.append(self.finding(
                ctx, stmt,
                f"bare device_put {where}: the upload dispatch runs on "
                "the consumer thread and lands in data_s — stage it "
                "through data.loader.DevicePrefetcher/prefetch_to_device "
                "(or pin with a reason if this copy is deliberate)"))


# ------------------------------------------------ DL101-DL104 concurrency
class SignalLockDeadlock(Rule):
    uses_graph = True
    id = "DL101"
    title = "plain Lock on a signal-handler path"
    rationale = ("a signal handler runs ON the main thread between "
                 "bytecodes; if it acquires a non-reentrant "
                 "threading.Lock that the interrupted main-thread code "
                 "was holding (the emit/sink fan-out), the process "
                 "self-deadlocks — exactly the PR-5 Ledger SIGTERM bug. "
                 "Use threading.RLock for any lock visible to a handler")

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        if "Lock(" not in ctx.src:
            return []   # cheap text gate: no lock construction here
        out: List[Finding] = []
        with graph_scope(project, ctx) as g:
            plain = {key: kind for key, kind in g.lock_attrs.items()
                     if key[0][0] == ctx.rel and kind == "Lock"}
            if not plain:
                return out
            hr = g.handler_reachable()
            ml = g.mainline_reachable()
            # acquire sites per (clskey, attr) in this file
            sites: Dict[tuple, List[tuple]] = {}
            for node in g.file_nodes(ctx.rel):
                if node.cls is None:
                    continue
                for owner, attr, line, col in node.lock_acquires:
                    if owner == "self" and (node.cls, attr) in plain:
                        sites.setdefault((node.cls, attr), []).append(
                            (node, line, col))
            for key, acqs in sites.items():
                handler_acqs = [a for a in acqs if a[0].qual in hr]
                main_acqs = [a for a in acqs if a[0].qual in ml]
                if not handler_acqs or not main_acqs:
                    continue
                clskey, attr = key
                for node, line, col in handler_acqs:
                    out.append(Finding(
                        self.id, ctx.rel, line, col,
                        f"non-reentrant threading.Lock "
                        f"'{clskey[1]}.{attr}' is acquired in "
                        f"{node.name}(), which is reachable from a signal "
                        f"handler, while the same lock guards main-thread "
                        f"call sites (e.g. {main_acqs[0][0].name}()); a "
                        "signal landing while the main thread holds it "
                        "self-deadlocks — use threading.RLock"))
        return out


class BlockingIoUnderLock(Rule):
    uses_graph = True
    id = "DL102"
    title = "blocking I/O while holding a shared lock"
    rationale = ("a sink/emit lock held across subprocess/socket/HTTP "
                 "calls or sleeps stalls every hot-path emit() caller "
                 "behind one slow syscall; move the I/O outside the "
                 "critical section (snapshot under the lock, write after)")
    severity = "warn"

    BLOCKING_IO_QUALS = {
        "time.sleep", "os.system",
        "subprocess.run", "subprocess.Popen", "subprocess.call",
        "subprocess.check_call", "subprocess.check_output",
        "socket.create_connection", "urllib.request.urlopen",
        "requests.get", "requests.post", "requests.request",
        "http.client.HTTPConnection", "http.client.HTTPSConnection",
    }
    # function names that put a method on the emit fan-out even when no
    # reachability evidence exists (ledger sinks are duck-typed callables)
    EMITISH = {"emit", "sink", "__call__"}

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        if "Lock(" not in ctx.src:
            return []   # cheap text gate: no lock construction here
        out: List[Finding] = []
        with graph_scope(project, ctx) as g:
            known = {key for key in g.lock_attrs if key[0][0] == ctx.rel}
            if not known:
                return out
            ml = g.mainline_reachable()
            acq_funcs: Dict[tuple, List] = {}
            for node in g.file_nodes(ctx.rel):
                if node.cls is None:
                    continue
                for owner, attr, _, _ in node.lock_acquires:
                    if owner == "self" and (node.cls, attr) in known:
                        acq_funcs.setdefault((node.cls, attr),
                                             []).append(node)
            for key, nodes in acq_funcs.items():
                on_emit_path = any(n.qual in ml or n.name in self.EMITISH
                                   for n in nodes)
                if not on_emit_path:
                    continue
                for node in nodes:
                    self._scan_with_blocks(node, key[1], ctx, out)
        return out

    def _scan_with_blocks(self, node, attr: str, ctx: FileContext,
                          out: List[Finding]) -> None:
        if node.node is None:
            return
        for n in ast.walk(node.node):
            if not isinstance(n, (ast.With, ast.AsyncWith)):
                continue
            holds = any(
                isinstance(i.context_expr, ast.Attribute)
                and terminal_name(i.context_expr) == attr
                and isinstance(i.context_expr.value, ast.Name)
                and i.context_expr.value.id == "self"
                for i in n.items)
            if not holds:
                continue
            for call in _calls_same_scope(n):
                qual = ctx.resolve(dotted_name(call.func))
                if qual in self.BLOCKING_IO_QUALS:
                    out.append(self.finding(
                        ctx, call,
                        f"blocking call '{qual}' executes while holding "
                        f"'self.{attr}', a lock the emit fan-out also "
                        "takes; every hot-path emitter stalls behind this "
                        "syscall — snapshot under the lock, do the I/O "
                        "after releasing it"))


class NonDaemonThreadNoJoin(Rule):
    uses_graph = True
    id = "DL103"
    title = "non-daemon thread with no join"
    rationale = ("a non-daemon thread with no join anywhere keeps the "
                 "interpreter alive after a crash: the run is dead, the "
                 "pod is billed, and the scheduler sees a healthy "
                 "process. Mark helpers daemon=True, or join the thread "
                 "on the shutdown path")
    severity = "warn"

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        if "Thread(" not in ctx.src:
            return []   # cheap text gate: no thread construction here
        out: List[Finding] = []
        with graph_scope(project, ctx) as g:
            recs = g.thread_ctors.get(ctx.rel, ())
            if not recs:
                return out
            # join matching is FILE-scoped on the receiver name: a
            # Watchdog joining its own '_thread' must not vouch for an
            # unrelated class's '_thread' in another file
            file_joins = {recv for qual, recv in g.join_sites
                          if qual.startswith(ctx.rel + "::")}
            # functions that join SOMETHING: a create-start-join worker
            # pattern in one function is bounded-lifetime, even when the
            # ctor (a comprehension, say) can't be bound to the receiver
            joining_funcs = {qual for qual, _ in g.join_sites}
            for rec in recs:
                if rec["daemon_true"]:
                    continue
                bind = rec["bind"]
                if bind and bind in file_joins:
                    continue
                if rec["qual"] in joining_funcs:
                    continue
                what = (f"thread bound to {bind!r}" if bind
                        else "unbound thread (constructed and started "
                             "inline)")
                out.append(Finding(
                    self.id, ctx.rel, rec["lineno"], rec["col"],
                    f"threading.Thread without daemon=True and without a "
                    f"join ({what}): if the run crashes, this thread "
                    "keeps the process alive forever — pass daemon=True "
                    "or join it on the run_end/shutdown path"))
        return out


class SignalHandlerHygiene(Rule):
    uses_graph = True
    id = "DL104"
    title = "unsafe signal handler body / dropped prior handler"
    rationale = ("logging and stream .flush() are not async-signal-safe "
                 "(a handler interrupting the io stack re-enters it and "
                 "corrupts or deadlocks); and installing a handler while "
                 "discarding signal.signal's return value silently drops "
                 "a previously-installed hook (a preemption checkpointer, "
                 "say) — capture and chain it")

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        out: List[Finding] = []
        with graph_scope(project, ctx) as g:
            handlers = {q for q in g.signal_handlers()
                        if q.startswith(ctx.rel + "::")}
            # the text gate only closes the file when the HANDLER root
            # set (memoized, cross-file) has nothing here either: a
            # handler body may live in a file that never says 'signal'
            # (installed from elsewhere), and install-site checks below
            # require the literal text by construction
            if not handlers and "signal" not in ctx.src:
                return out
            for node in g.file_nodes(ctx.rel):
                if node.qual in handlers and node.node is not None:
                    self._scan_handler_body(node, ctx, out)
            for rec in g.signal_installs.get(ctx.rel, ()):
                self._check_chaining(rec, g, ctx, out)
        return out

    def _scan_handler_body(self, node, ctx: FileContext,
                           out: List[Finding]) -> None:
        for call in _calls_same_scope(node.node):
            qual = ctx.resolve(dotted_name(call.func))
            tname = terminal_name(call.func)
            hit = None
            if qual.split(".")[0] == "logging" or (
                    qual.startswith("log") and tname in (
                        "debug", "info", "warning", "error", "exception",
                        "critical")):
                hit = f"logging call '{qual}'"
            elif tname == "flush":
                hit = f"stream flush '{dotted_name(call.func)}()'"
            if hit:
                out.append(self.finding(
                    ctx, call,
                    f"{hit} inside the signal handler {node.name}(): "
                    "logging/io are not reentrant — a signal landing "
                    "mid-write re-enters the io stack and corrupts or "
                    "deadlocks; set a flag and do the work on the main "
                    "code path"))

    def _check_chaining(self, rec, g, ctx: FileContext,
                        out: List[Finding]) -> None:
        if rec["result_used"]:
            return
        handler = rec["handler"]
        installs_new = isinstance(handler, ast.Lambda)
        if isinstance(handler, (ast.Name, ast.Attribute)):
            node = g.funcs.get(rec["qual"])
            if node is not None:
                targets, _ = g.resolve(node, dotted_name(handler))
                installs_new = bool(targets)
        if installs_new:
            out.append(Finding(
                self.id, ctx.rel, rec["lineno"], rec["col"],
                "signal.signal() installs a new handler but discards the "
                "return value: any previously-installed handler (a "
                "preemption checkpoint hook, a supervisor's own cleanup) "
                "is silently dropped — capture the previous handler and "
                "chain it from yours"))


# ------------------------------------------------------------------ DL201
class DivergentBranchCollectives(Rule):
    uses_graph = True
    id = "DL201"
    title = "cond/switch branches issue divergent collective sequences"
    rationale = ("under SPMD every process must execute the SAME ordered "
                 "collective sequence; if lax.cond branches disagree (psum "
                 "then pmax vs pmax then psum, or a collective in one arm "
                 "only) any per-process predicate divergence pairs "
                 "mismatched collectives across hosts and the pod "
                 "deadlocks — the MPI matching rule, provable statically")

    # primitives that rendezvous across processes when traced: the jaxpr
    # half of this check lives in tpu_dist/analysis/proglint.py (PL002);
    # this is the source-level prover over the same failure class
    COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                   "all_to_all", "ppermute", "pbroadcast", "psum_scatter",
                   "axis_index"}
    _BRANCH_CALLS = {"cond", "switch"}

    def check(self, ctx: FileContext, project: Project) -> List[Finding]:
        if "cond" not in ctx.src and "switch" not in ctx.src:
            return []
        out: List[Finding] = []
        with graph_scope(project, ctx) as g:
            for node in g.file_nodes(ctx.rel):
                root = ctx.tree if node.name == "<module>" else node.node
                if root is None:
                    continue
                for call in _calls_same_scope(root):
                    if terminal_name(call.func) in self._BRANCH_CALLS:
                        self._check_site(call, node, g, ctx, out)
        return out

    def _check_site(self, call: ast.Call, encl, g, ctx: FileContext,
                    out: List[Finding]) -> None:
        tname = terminal_name(call.func)
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        if tname == "cond":
            branches = list(call.args[1:3])
            for name in ("true_fun", "false_fun"):
                if name in kw:
                    branches.append(kw[name])
            labels = ("true branch", "false branch")
            if len(branches) != 2:
                return
        else:
            seq_arg = (call.args[1] if len(call.args) > 1
                       else kw.get("branches"))
            if not isinstance(seq_arg, (ast.Tuple, ast.List)):
                return
            branches = list(seq_arg.elts)
            labels = tuple(f"branch[{i}]" for i in range(len(branches)))
            if len(branches) < 2:
                return
        seqs = []
        for b in branches:
            seq = self._branch_sequence(b, encl, g)
            if seq is None:
                return   # unresolvable callable: stay silent, no guess
            seqs.append(seq)
        if len(set(seqs)) <= 1 or not any(seqs):
            return
        desc = "; ".join(f"{lab} {self._fmt(s)}"
                         for lab, s in zip(labels, seqs))
        out.append(self.finding(
            ctx, call,
            f"lax.{tname} branches issue different ordered collective "
            f"sequences ({desc}); a process taking the other branch "
            "pairs mismatched collectives across hosts and the pod "
            "deadlocks — make every branch issue the identical sequence "
            "(pad with the same collectives on a zero operand if needed)"))

    def _branch_sequence(self, node: ast.AST, encl, g,
                         _depth: int = 0,
                         _seen: Optional[Set[str]] = None):
        """Ordered (collective, axes...) tuples a branch callable issues,
        or None when the callable cannot be resolved. Name/Attribute refs
        resolve through the call graph (one level of helper recursion,
        cycle-guarded); lambdas and functools.partial heads inline."""
        if _seen is None:
            _seen = set()
        if isinstance(node, ast.Lambda):
            return self._sequence(node, encl, g, _depth, _seen)
        if (isinstance(node, ast.Call)
                and terminal_name(node.func) == "partial" and node.args):
            return self._branch_sequence(node.args[0], encl, g,
                                         _depth, _seen)
        if isinstance(node, (ast.Name, ast.Attribute)):
            targets, _ = g.resolve(encl, dotted_name(node))
            for t in targets:
                fn = g.funcs.get(t)
                if fn is not None and fn.node is not None:
                    if t in _seen:
                        return ()
                    _seen.add(t)
                    return self._sequence(fn.node, fn, g, _depth, _seen)
        return None

    def _sequence(self, root: ast.AST, owner, g, depth: int,
                  seen: Set[str]) -> tuple:
        calls = sorted(_calls_same_scope(root),
                       key=lambda c: (c.lineno, c.col_offset))
        seq: List[tuple] = []
        for c in calls:
            tn = terminal_name(c.func)
            if tn in self.COLLECTIVES:
                seq.append((tn,) + self._axes(c, tn))
            elif depth < 1 and owner is not None:
                targets, _ = g.resolve(owner, dotted_name(c.func))
                for t in targets:
                    fn = g.funcs.get(t)
                    if fn is not None and fn.node is not None \
                            and t not in seen:
                        seen.add(t)
                        seq.extend(self._sequence(fn.node, fn, g,
                                                  depth + 1, seen))
                        break
        return tuple(seq)

    def _axes(self, call: ast.Call, tname: str) -> tuple:
        pos = 0 if tname in ("axis_index", "axis_size") else 1
        cands = list(call.args[pos:pos + 1]) + [
            k.value for k in call.keywords
            if k.arg in ("axis_name", "axis", "axes")]
        out: List[str] = []

        def walk(nodes) -> None:
            for n in nodes:
                if isinstance(n, (ast.Tuple, ast.List)):
                    walk(n.elts)
                elif isinstance(n, ast.Constant) and isinstance(n.value,
                                                                str):
                    out.append(n.value)
        walk(cands)
        return tuple(out)

    def _fmt(self, seq: tuple) -> str:
        if not seq:
            return "[no collectives]"
        return "[" + " -> ".join(
            f"{s[0]}({','.join(s[1:])})" for s in seq) + "]"


RULES: List[Rule] = [HostDivergentCollectives(), HotLoopHostSync(),
                     UnknownMeshAxis(), TracedSideEffect(), PrngHygiene(),
                     LedgerSchema(), DonatedBufferReuse(),
                     HotLoopDevicePut(),
                     SignalLockDeadlock(), BlockingIoUnderLock(),
                     NonDaemonThreadNoJoin(), SignalHandlerHygiene(),
                     DivergentBranchCollectives()]

RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in RULES}
