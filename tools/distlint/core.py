"""distlint engine: file walking, suppressions, rule running, output.

Stdlib-only by contract (``ast`` + ``tokenize``; no jax import anywhere in
the package): the linter must run in CI containers and pre-commit hooks
that have no accelerator stack, and importing the checked modules would
initialize a backend. Everything the rules need from the repo (mesh axis
names, the ledger event schema) is extracted from SOURCE by AST — the same
trick ``tools/check_ledger_schema.py`` proved out, generalized.

Vocabulary:

* a :class:`Finding` is one violation at ``path:line:col`` with a rule id;
* a suppression is an inline comment ``# distlint: disable=DL002 -- reason``
  (trailing on the flagged line, or standalone on the line above). The
  reason is REQUIRED — a bare disable is itself a finding (DL000), because
  an unexplained suppression is indistinguishable from a stale one;
* :class:`Project` lazily loads cross-file facts (mesh axes, event schema)
  relative to the repo root, so rules stay pure functions of (file, facts).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

MESH_FILE = os.path.join("tpu_dist", "parallel", "mesh.py")
SCHEMA_FILE = os.path.join("tpu_dist", "obs", "ledger.py")

# directory names never entered by the walker (explicit file arguments are
# always linted — that is how the test fixtures get checked without the
# clean-tree sweep tripping over their deliberately bad code)
SKIP_DIRS = {"__pycache__", ".git", "fixtures", "node_modules", ".venv"}

# meta-rule id: malformed suppressions, unparseable files. Not suppressible.
META_RULE = "DL000"

_SUPPRESS_RE = re.compile(
    r"^#\s*distlint:\s*disable=(?P<rules>DL\d{3}(?:\s*,\s*DL\d{3})*)"
    r"(?:\s*--\s*(?P<reason>.*\S))?\s*$")
# directive recognition is anchored: only comments STARTING with
# '# distlint:' are directives, so prose mentioning the tool stays inert
_SUPPRESS_HINT_RE = re.compile(r"^#\s*distlint\s*:")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str
    path: str       # repo-relative, '/'-separated
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}


@dataclass(frozen=True)
class Suppression:
    """A parsed ``# distlint: disable=...`` comment bound to a code line."""
    rules: Tuple[str, ...]
    reason: str
    line: int        # the code line it applies to
    comment_line: int

    def to_json(self) -> dict:
        return {"rules": list(self.rules), "reason": self.reason,
                "line": self.line, "comment_line": self.comment_line}


class FileContext:
    """Per-file parse products shared by every rule (one AST, one token
    pass per file — rules never re-read the source)."""

    def __init__(self, path: str, rel: str, src: str):
        self.path = path
        self.rel = rel
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src)            # may raise SyntaxError
        self.import_aliases = _import_aliases(self.tree)

    def line_text(self, lineno: int) -> str:
        return self.lines[lineno - 1] if 0 < lineno <= len(self.lines) else ""

    def resolve(self, qualname: str) -> str:
        """Expand the leading import alias of a dotted name:
        ``np.random.seed`` -> ``numpy.random.seed`` under ``import numpy
        as np``; ``random.split`` -> ``jax.random.split`` under ``from jax
        import random``. Unknown heads pass through unchanged."""
        if not qualname:
            return qualname
        head, sep, rest = qualname.partition(".")
        target = self.import_aliases.get(head)
        if target is None:
            return qualname
        return target + sep + rest if sep else target


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.partition(".")[0]] = (
                    a.name if a.asname else a.name.partition(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


# --------------------------------------------------------------- project
class Project:
    """Cross-file facts, loaded lazily from source by AST (never imported)."""

    def __init__(self, root: str = REPO_ROOT):
        self.root = os.path.abspath(root)
        self._mesh_axes: Optional[Set[str]] = None
        self._event_schema: Optional[Dict[str, tuple]] = None

    @property
    def mesh_axes(self) -> Set[str]:
        """Axis-name literals declared as ``*_AXIS = "..."`` in
        tpu_dist/parallel/mesh.py — THE authority DL003 validates against.
        Empty set (file absent) disables DL003 rather than flagging
        everything."""
        if self._mesh_axes is None:
            self._mesh_axes = load_mesh_axes(self.root)
        return self._mesh_axes

    @property
    def event_schema(self) -> Dict[str, tuple]:
        if self._event_schema is None:
            self._event_schema = load_event_schema(self.root)
        return self._event_schema


def load_mesh_axes(root: str = REPO_ROOT) -> Set[str]:
    path = os.path.join(root, MESH_FILE)
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        tree = ast.parse(f.read())
    axes: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Name) and t.id.endswith("_AXIS")
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    axes.add(node.value.value)
    return axes


def load_event_schema(root: str = REPO_ROOT) -> Dict[str, tuple]:
    """EVENT_SCHEMA extracted from ledger.py source — a pure literal by
    that dict's own contract."""
    path = os.path.join(root, SCHEMA_FILE)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "EVENT_SCHEMA":
                    return ast.literal_eval(node.value)
    raise AssertionError(f"EVENT_SCHEMA literal not found in {path}")


# ---------------------------------------------------------- suppressions
def parse_suppressions(src: str) -> Tuple[List[Suppression], List[Tuple[int, str]]]:
    """(suppressions, malformed) from the token stream.

    A trailing comment suppresses its own line; a standalone comment (the
    line holds nothing else) suppresses the next non-blank, non-comment
    line. ``malformed`` is (line, problem) pairs for distlint comments that
    fail the grammar — most importantly a missing ``-- reason``.
    """
    sups: List[Suppression] = []
    malformed: List[Tuple[int, str]] = []
    lines = src.splitlines()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return sups, malformed
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        text = tok.string
        if not _SUPPRESS_HINT_RE.search(text):
            continue
        lineno = tok.start[0]
        m = _SUPPRESS_RE.search(text)
        if "disable" not in text:
            # other distlint directives don't exist (yet): flag typos like
            # '# distlint: off' instead of silently ignoring them
            malformed.append((lineno, f"unrecognized distlint directive "
                                      f"{text.strip()!r} (only "
                                      "'disable=DLxxx -- reason' exists)"))
            continue
        if m is None or not (m.group("reason") or "").strip():
            malformed.append(
                (lineno, "suppression must carry a reason: "
                         "'# distlint: disable=DLxxx -- <why this is ok>'"))
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(","))
        before = lines[lineno - 1][:tok.start[1]]
        if before.strip():
            target = lineno                       # trailing comment
        else:                                     # standalone: next code line
            target = lineno
            for j in range(lineno + 1, len(lines) + 1):
                s = lines[j - 1].strip()
                if s and not s.startswith("#"):
                    target = j
                    break
        sups.append(Suppression(rules=rules, reason=m.group("reason").strip(),
                                line=target, comment_line=lineno))
    return sups, malformed


# --------------------------------------------------------------- linting
@dataclass
class LintResult:
    findings: List[Finding]
    suppressed: List[Tuple[Finding, Suppression]]
    files_checked: int

    def to_json(self) -> dict:
        return {
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [dict(f.to_json(), reason=s.reason)
                           for f, s in self.suppressed],
            "files_checked": self.files_checked,
        }


def iter_python_files(paths: Sequence[str], root: str) -> List[str]:
    """Expand dirs (recursively, skipping SKIP_DIRS) and keep explicit .py
    file arguments as-is. Paths may be absolute or root-relative."""
    out: List[str] = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            out.append(full)
        elif os.path.isdir(full):
            for dirpath, dirnames, files in os.walk(full):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in SKIP_DIRS)
                out += [os.path.join(dirpath, f) for f in sorted(files)
                        if f.endswith(".py")]
        else:
            raise FileNotFoundError(f"distlint: no such path: {p}")
    seen, uniq = set(), []
    for f in out:
        if f not in seen:
            seen.add(f)
            uniq.append(f)
    return uniq


def lint_files(paths: Sequence[str], root: str = REPO_ROOT,
               select: Optional[Iterable[str]] = None,
               project: Optional[Project] = None) -> LintResult:
    """Run the (selected) rules over every file under ``paths``."""
    from tools.distlint.rules import RULES

    project = project or Project(root)
    selected = [r for r in RULES
                if select is None or r.id in set(select)]
    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, Suppression]] = []
    files = iter_python_files(paths, project.root)
    for path in files:
        rel = os.path.relpath(path, project.root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            src = f.read()
        sups, malformed = parse_suppressions(src)
        for line, problem in malformed:
            findings.append(Finding(META_RULE, rel, line, 0, problem))
        try:
            ctx = FileContext(path, rel, src)
        except SyntaxError as e:
            findings.append(Finding(META_RULE, rel, e.lineno or 0, 0,
                                    f"unparseable: {e.msg}"))
            continue
        by_line: Dict[int, List[Suppression]] = {}
        for s in sups:
            # a suppression bound to ANY physical line of a multi-line
            # statement covers the whole statement: findings anchor to the
            # node's first line, while a trailing comment (or a formatter
            # re-wrap) may sit on a continuation line
            for line in _statement_span(ctx.tree, s.line):
                by_line.setdefault(line, []).append(s)
        for rule in selected:
            for f in rule.check(ctx, project):
                hit = next((s for s in by_line.get(f.line, ())
                            if f.rule in s.rules), None)
                if hit is not None:
                    suppressed.append((f, hit))
                else:
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(findings, suppressed, len(files))


def _statement_span(tree: ast.AST, line: int) -> range:
    """Physical-line range of the innermost SIMPLE statement containing
    ``line`` (compound statements — defs, ifs, loops — are skipped: a
    suppression inside one must not blanket its whole body). Falls back to
    the single line itself."""
    best = None
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt) or isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                       ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
                       ast.AsyncWith, ast.Try)):
            continue
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= line <= end and (
                best is None or node.lineno > best[0]):
            best = (node.lineno, end)
    if best is None:
        return range(line, line + 1)
    return range(best[0], best[1] + 1)


# ----------------------------------------------------------- ast helpers
def dotted_name(node: ast.AST) -> str:
    """Dotted receiver chain: ``jax.random.split`` -> 'jax.random.split',
    ``self.obs.ledger`` -> 'self.obs.ledger'. Non-name roots (calls,
    subscripts) contribute an empty head: ``foo().bar`` -> '.bar'."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    parts.append(node.id if isinstance(node, ast.Name) else "")
    return ".".join(reversed(parts))


def terminal_name(node: ast.AST) -> str:
    """The final component of a name/attribute chain ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""
