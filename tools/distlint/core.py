"""distlint engine: file walking, suppressions, rule running, output.

Stdlib-only by contract (``ast`` + ``tokenize``; no jax import anywhere in
the package): the linter must run in CI containers and pre-commit hooks
that have no accelerator stack, and importing the checked modules would
initialize a backend. Everything the rules need from the repo (mesh axis
names, the ledger event schema) is extracted from SOURCE by AST — the same
trick ``tools/check_ledger_schema.py`` proved out, generalized.

Vocabulary:

* a :class:`Finding` is one violation at ``path:line:col`` with a rule id;
* a suppression is an inline comment ``# distlint: disable=DL002 -- reason``
  (trailing on the flagged line, or standalone on the line above). The
  reason is REQUIRED — a bare disable is itself a finding (DL000), because
  an unexplained suppression is indistinguishable from a stale one;
* :class:`Project` lazily loads cross-file facts (mesh axes, event schema)
  relative to the repo root, so rules stay pure functions of (file, facts).
"""

from __future__ import annotations

import ast
import contextlib
import hashlib
import io
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

MESH_FILE = os.path.join("tpu_dist", "parallel", "mesh.py")
SCHEMA_FILE = os.path.join("tpu_dist", "obs", "ledger.py")

# directory names never entered by the walker (explicit file arguments are
# always linted — that is how the test fixtures get checked without the
# clean-tree sweep tripping over their deliberately bad code)
SKIP_DIRS = {"__pycache__", ".git", "fixtures", "node_modules", ".venv"}

# meta-rule id: malformed suppressions, unparseable files. Not suppressible.
META_RULE = "DL000"

_SUPPRESS_RE = re.compile(
    r"^#\s*distlint:\s*disable=(?P<rules>DL\d{3}(?:\s*,\s*DL\d{3})*)"
    r"(?:\s*--\s*(?P<reason>.*\S))?\s*$")
# directive recognition is anchored: only comments STARTING with
# '# distlint:' are directives, so prose mentioning the tool stays inert
_SUPPRESS_HINT_RE = re.compile(r"^#\s*distlint\s*:")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str
    path: str       # repo-relative, '/'-separated
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}


@dataclass(frozen=True)
class Suppression:
    """A parsed ``# distlint: disable=...`` comment bound to a code line."""
    rules: Tuple[str, ...]
    reason: str
    line: int        # the code line it applies to
    comment_line: int

    def to_json(self) -> dict:
        return {"rules": list(self.rules), "reason": self.reason,
                "line": self.line, "comment_line": self.comment_line}


def _expand_alias(aliases: Dict[str, str], head: str) -> str:
    """Expand the leading import alias of a dotted name against
    ``aliases``: ``np.random.seed`` -> ``numpy.random.seed`` under
    ``import numpy as np``. Unknown heads pass through unchanged. The
    ONE implementation of this semantics — FileContext.resolve and the
    call-graph recorders all route here so they cannot drift."""
    first, sep, rest = head.partition(".")
    target = aliases.get(first, first)
    return target + sep + rest if sep else target


class FileContext:
    """Per-file parse products shared by every rule (one AST, one token
    pass per file — rules never re-read the source)."""

    def __init__(self, path: str, rel: str, src: str):
        self.path = path
        self.rel = rel
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src)            # may raise SyntaxError
        self.import_aliases = _import_aliases(self.tree)

    def line_text(self, lineno: int) -> str:
        return self.lines[lineno - 1] if 0 < lineno <= len(self.lines) else ""

    def resolve(self, qualname: str) -> str:
        """Expand the leading import alias of a dotted name:
        ``np.random.seed`` -> ``numpy.random.seed`` under ``import numpy
        as np``; ``random.split`` -> ``jax.random.split`` under ``from jax
        import random``. Unknown heads pass through unchanged."""
        if not qualname:
            return qualname
        return _expand_alias(self.import_aliases, qualname)


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.partition(".")[0]] = (
                    a.name if a.asname else a.name.partition(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


# --------------------------------------------------------------- project
class Project:
    """Cross-file facts, loaded lazily from source by AST (never imported)."""

    def __init__(self, root: str = REPO_ROOT):
        self.root = os.path.abspath(root)
        self._mesh_axes: Optional[Set[str]] = None
        self._event_schema: Optional[Dict[str, tuple]] = None

    @property
    def mesh_axes(self) -> Set[str]:
        """Axis-name literals declared as ``*_AXIS = "..."`` in
        tpu_dist/parallel/mesh.py — THE authority DL003 validates against.
        Empty set (file absent) disables DL003 rather than flagging
        everything."""
        if self._mesh_axes is None:
            self._mesh_axes = load_mesh_axes(self.root)
        return self._mesh_axes

    @property
    def event_schema(self) -> Dict[str, tuple]:
        if self._event_schema is None:
            self._event_schema = load_event_schema(self.root)
        return self._event_schema

    @property
    def callgraph(self) -> "CallGraph":
        """The cross-file call graph + reachability engine (lazy,
        process-cached — see :func:`load_callgraph`)."""
        return load_callgraph(self.root)


def load_mesh_axes(root: str = REPO_ROOT) -> Set[str]:
    path = os.path.join(root, MESH_FILE)
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        tree = ast.parse(f.read())
    axes: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Name) and t.id.endswith("_AXIS")
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    axes.add(node.value.value)
    return axes


def load_event_schema(root: str = REPO_ROOT) -> Dict[str, tuple]:
    """EVENT_SCHEMA extracted from ledger.py source — a pure literal by
    that dict's own contract."""
    path = os.path.join(root, SCHEMA_FILE)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "EVENT_SCHEMA":
                    return ast.literal_eval(node.value)
    raise AssertionError(f"EVENT_SCHEMA literal not found in {path}")


# ---------------------------------------------------------- suppressions
def parse_suppressions(src: str) -> Tuple[List[Suppression], List[Tuple[int, str]]]:
    """(suppressions, malformed) from the token stream.

    A trailing comment suppresses its own line; a standalone comment (the
    line holds nothing else) suppresses the next non-blank, non-comment
    line. ``malformed`` is (line, problem) pairs for distlint comments that
    fail the grammar — most importantly a missing ``-- reason``.
    """
    sups: List[Suppression] = []
    malformed: List[Tuple[int, str]] = []
    lines = src.splitlines()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return sups, malformed
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        text = tok.string
        if not _SUPPRESS_HINT_RE.search(text):
            continue
        lineno = tok.start[0]
        m = _SUPPRESS_RE.search(text)
        if "disable" not in text:
            # other distlint directives don't exist (yet): flag typos like
            # '# distlint: off' instead of silently ignoring them
            malformed.append((lineno, f"unrecognized distlint directive "
                                      f"{text.strip()!r} (only "
                                      "'disable=DLxxx -- reason' exists)"))
            continue
        if m is None or not (m.group("reason") or "").strip():
            malformed.append(
                (lineno, "suppression must carry a reason: "
                         "'# distlint: disable=DLxxx -- <why this is ok>'"))
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(","))
        before = lines[lineno - 1][:tok.start[1]]
        if before.strip():
            target = lineno                       # trailing comment
        else:                                     # standalone: next code line
            target = lineno
            for j in range(lineno + 1, len(lines) + 1):
                s = lines[j - 1].strip()
                if s and not s.startswith("#"):
                    target = j
                    break
        sups.append(Suppression(rules=rules, reason=m.group("reason").strip(),
                                line=target, comment_line=lineno))
    return sups, malformed


# --------------------------------------------------------------- linting
@dataclass
class LintResult:
    findings: List[Finding]
    suppressed: List[Tuple[Finding, Suppression]]
    files_checked: int

    def to_json(self) -> dict:
        return {
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [dict(f.to_json(), reason=s.reason)
                           for f, s in self.suppressed],
            "files_checked": self.files_checked,
        }


def iter_python_files(paths: Sequence[str], root: str) -> List[str]:
    """Expand dirs (recursively, skipping SKIP_DIRS) and keep explicit .py
    file arguments as-is. Paths may be absolute or root-relative."""
    out: List[str] = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            out.append(full)
        elif os.path.isdir(full):
            for dirpath, dirnames, files in os.walk(full):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in SKIP_DIRS)
                out += [os.path.join(dirpath, f) for f in sorted(files)
                        if f.endswith(".py")]
        else:
            raise FileNotFoundError(f"distlint: no such path: {p}")
    seen, uniq = set(), []
    for f in out:
        if f not in seen:
            seen.add(f)
            uniq.append(f)
    return uniq


def lint_files(paths: Sequence[str], root: str = REPO_ROOT,
               select: Optional[Iterable[str]] = None,
               project: Optional[Project] = None) -> LintResult:
    """Run the (selected) rules over every file under ``paths``."""
    from tools.distlint.rules import RULES

    project = project or Project(root)
    selected = [r for r in RULES
                if select is None or r.id in set(select)]
    # one graph overlay add/remove per FILE, not per graph-backed rule:
    # the rules' own graph_scope calls become no-ops (ensure_file is
    # idempotent), so an out-of-surface file is indexed once and the
    # version-keyed reachability memos survive all five rule passes
    needs_graph = any(r.uses_graph for r in selected)
    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, Suppression]] = []
    files = iter_python_files(paths, project.root)
    for path in files:
        rel = os.path.relpath(path, project.root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            src = f.read()
        # tokenizing is the expensive half of suppression parsing; only
        # files that mention the directive at all need it
        sups, malformed = (parse_suppressions(src)
                           if "distlint" in src else ([], []))
        for line, problem in malformed:
            findings.append(Finding(META_RULE, rel, line, 0, problem))
        try:
            ctx = FileContext(path, rel, src)
        except SyntaxError as e:
            findings.append(Finding(META_RULE, rel, e.lineno or 0, 0,
                                    f"unparseable: {e.msg}"))
            continue
        by_line: Dict[int, List[Suppression]] = {}
        for s in sups:
            # a suppression bound to ANY physical line of a multi-line
            # statement covers the whole statement: findings anchor to the
            # node's first line, while a trailing comment (or a formatter
            # re-wrap) may sit on a continuation line
            for line in _statement_span(ctx.tree, s.line):
                by_line.setdefault(line, []).append(s)
        with (graph_scope(project, ctx) if needs_graph
              else contextlib.nullcontext()):
            for rule in selected:
                for f in rule.check(ctx, project):
                    hit = next((s for s in by_line.get(f.line, ())
                                if f.rule in s.rules), None)
                    if hit is not None:
                        suppressed.append((f, hit))
                    else:
                        findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(findings, suppressed, len(files))


def _statement_span(tree: ast.AST, line: int) -> range:
    """Physical-line range of the innermost SIMPLE statement containing
    ``line`` (compound statements — defs, ifs, loops — are skipped: a
    suppression inside one must not blanket its whole body). Falls back to
    the single line itself."""
    best = None
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt) or isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                       ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
                       ast.AsyncWith, ast.Try)):
            continue
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= line <= end and (
                best is None or node.lineno > best[0]):
            best = (node.lineno, end)
    if best is None:
        return range(line, line + 1)
    return range(best[0], best[1] + 1)


# ------------------------------------------------------------ call graph
# Cross-file reachability engine (stdlib-only, like every other Project
# fact): intra-repo def/call edges extracted by AST with import-alias and
# attribute-type resolution, plus the ROOT SETS the DL1xx concurrency
# rules and DL002's hot-path derivation need — traced (jit/shard_map)
# functions, signal handlers, thread targets, atexit/excepthook hooks, and
# "escaped" callbacks (function references handed to registration calls,
# e.g. ledger sinks). Precision contract: resolution is best-effort and
# DELIBERATELY over-approximate where types are unknown (a method call on
# an untyped receiver falls back to every project method of that name,
# minus a stdlib-noise stoplist); rules built on it must therefore pair a
# reachability condition with a syntactic one (e.g. DL101: *plain* Lock
# AND handler-reachable AND mainline acquire) so over-approximation can
# only widen an already-real hazard, not invent one from nothing.

# the project surface the base graph indexes (missing entries skipped —
# tests build graphs against tmp roots too)
GRAPH_SURFACE = ("tpu_dist", "tools", "scripts", "tests", "bench.py")

# terminal method names excluded from the by-name fallback: they are
# overwhelmingly stdlib container/IO calls, and an edge from every
# `x.get()` to every project method named `get` would drown the graph
_FALLBACK_NOISE = frozenset({
    "append", "extend", "pop", "get", "items", "keys", "values", "join",
    "split", "strip", "startswith", "endswith", "format", "write", "read",
    "flush", "close", "add", "update", "copy", "sort", "index", "count",
    "insert", "remove", "clear", "setdefault", "popitem", "encode",
    "decode", "open", "exists", "put", "start", "wait", "set", "acquire",
    "release", "lower", "upper", "replace", "reshape", "astype", "mean",
    "sum", "min", "max", "item", "tolist", "numpy", "block_until_ready",
})

_TRACER_NAMES = ("jit", "pjit")


def _is_tracer_head(head: str) -> bool:
    t = head.rpartition(".")[2]
    return t in _TRACER_NAMES or "shard_map" in t


class FuncNode:
    """One function/method (or the module pseudo-node ``<module>``) in the
    call graph, with everything resolution needs recorded at build time."""

    __slots__ = (
        "qual", "rel", "name", "cls", "node", "lineno", "parent",
        "children", "calls", "arg_refs", "factory_args", "local_types",
        "local_traced", "local_assign_calls", "lock_acquires", "loops",
        "return_calls", "returns_jit", "return_class", "aliases")

    def __init__(self, qual, rel, name, cls, node, lineno, parent,
                 aliases):
        self.qual = qual
        self.rel = rel
        self.name = name
        self.cls = cls                 # (rel, clsname) or None
        self.node = node               # ast def node (None for <module>)
        self.lineno = lineno
        self.parent = parent           # enclosing FuncNode or None
        self.children: Dict[str, str] = {}       # nested def name -> qual
        self.calls: List[Tuple[str, int]] = []   # (dotted head, lineno)
        self.arg_refs: List[str] = []  # Name/Attribute refs passed as args
        self.factory_args: List[str] = []  # heads of calls whose RESULT is
        #                                    passed as an argument
        self.local_types: Dict[str, tuple] = {}  # var -> (rel, clsname)
        self.local_traced: Set[str] = set()      # var = jax.jit(...)
        self.local_assign_calls: Dict[str, str] = {}  # var -> call head
        self.lock_acquires: List[Tuple[str, str, int, int]] = []
        #   (owner 'self'|'name', attr-or-name, lineno, col)
        self.loops: List[ast.AST] = []   # same-scope For/While statements
        self.return_calls: List[str] = []
        self.returns_jit = False
        self.return_class: Optional[str] = None  # 'ClassName' literal ctor
        self.aliases = aliases         # module import table (shared)


class CallGraph:
    """Lazily built, incrementally extendable cross-file call graph.

    Files inside :data:`GRAPH_SURFACE` are indexed once per process (see
    :func:`load_callgraph`); out-of-surface files (rule fixtures, tmp
    snippets) are added per check via :meth:`ensure_file` and removed
    again with :meth:`remove_file` so tests stay isolated. Derived sets
    (reachability closures) are memoized per graph version."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.funcs: Dict[str, FuncNode] = {}
        self.file_quals: Dict[str, List[str]] = {}       # rel -> quals
        self.file_digest: Dict[str, str] = {}            # rel -> src sha1
        self.module_of: Dict[str, str] = {}              # module name -> rel
        self.module_funcs: Dict[Tuple[str, str], str] = {}
        self.module_traced: Set[Tuple[str, str]] = set()
        self.classes: Dict[tuple, Dict[str, str]] = {}   # clskey -> methods
        self.class_alias: Dict[Tuple[str, str], tuple] = {}  # (rel, name)
        self.methods_by_name: Dict[str, List[str]] = {}
        self.attr_types: Dict[tuple, tuple] = {}     # (clskey, attr) -> cls
        self.attr_assign_calls: Dict[tuple, str] = {}  # (clskey, attr) -> head
        self.attr_traced: Set[tuple] = set()         # (clskey, attr)
        self.lock_attrs: Dict[tuple, str] = {}       # (clskey, attr) -> kind
        self.signal_handler_heads: List[Tuple[str, str]] = []  # (qual, head)
        self.signal_installs: Dict[str, list] = {}   # rel -> install records
        self.thread_ctors: Dict[str, list] = {}      # rel -> ctor records
        self.join_sites: List[Tuple[str, str]] = []  # (qual, receiver tail)
        self.atexit_heads: List[Tuple[str, str]] = []
        self.hook_assign_heads: List[Tuple[str, str]] = []  # sys.excepthook=
        self.decorated_traced: Set[str] = set()
        self.jit_mark_heads: List[Tuple[str, str]] = []  # jit(f) name marks
        self._version = 0
        # files added AFTER the base build (fixtures, tmp snippets): the
        # by-name fallback never resolves INTO them from another file, so
        # base-file edges are identical whether or not an overlay happens
        # to be present (and whenever the edge cache was populated)
        self.overlay_files: Set[str] = set()
        self._base_built = False
        self._edges: Dict[str, Tuple[tuple, bool]] = {}  # qual -> (targets,
        #                                                  dispatches_traced)
        self._memo: Dict[str, Tuple[int, object]] = {}
        # in-flight (node id, head) pairs while following local var
        # assignments: `x = x()` (or mutual a=b(); b=a()) must not send
        # resolve()/_resolve_bare() into unbounded recursion
        self._resolving: Set[Tuple[int, str]] = set()
        # (rel, lineno) -> assignment target of a threading.Thread(...)
        # RHS; statements visit parent-first, so the bind is recorded here
        # before the Call node creates its ctor record and consumes it
        self._pending_thread_binds: Dict[Tuple[str, int], str] = {}

    # -- build ----------------------------------------------------------
    def ensure_file(self, rel: str, tree: Optional[ast.AST] = None,
                    path: Optional[str] = None,
                    src: Optional[str] = None) -> bool:
        """Index one file (idempotent); returns True when it was newly
        added (caller pairs with :meth:`remove_file` for isolation).

        An already-indexed file whose ``src`` digest no longer matches is
        re-indexed in place (same overlay/base status, version bumped):
        the graph is process-cached, so a same-process re-lint of a file
        that changed on disk must not serve facts — or finding line
        numbers — from the stale parse."""
        digest = (hashlib.sha1(src.encode("utf-8", "replace")).hexdigest()
                  if src is not None else None)
        if rel in self.file_quals:
            if digest is None or self.file_digest.get(rel) == digest:
                return False
            if tree is None:
                try:
                    tree = ast.parse(src)
                except SyntaxError:
                    return False
            was_overlay = rel in self.overlay_files
            self.remove_file(rel)
            self._index_file(rel, tree)
            self.file_digest[rel] = digest
            if was_overlay:
                self.overlay_files.add(rel)
            self._version += 1
            return False
        if self._base_built:
            self.overlay_files.add(rel)
        if tree is None and src is None:
            full = path or os.path.join(self.root, rel)
            try:
                with open(full, encoding="utf-8") as f:
                    src = f.read()
            except OSError:
                self.file_quals[rel] = []
                return True
            digest = hashlib.sha1(
                src.encode("utf-8", "replace")).hexdigest()
        if tree is None:
            try:
                tree = ast.parse(src)
            except SyntaxError:
                self.file_quals[rel] = []
                return True
        self._index_file(rel, tree)
        if digest is not None:
            self.file_digest[rel] = digest
        self._version += 1
        return True

    def remove_file(self, rel: str) -> None:
        self.overlay_files.discard(rel)
        self.file_digest.pop(rel, None)
        quals = self.file_quals.pop(rel, None)
        if quals is None:
            return
        for q in quals:
            n = self.funcs.pop(q, None)
            self._edges.pop(q, None)
            if n is not None and n.cls is not None:
                lst = self.methods_by_name.get(n.name)
                if lst and q in lst:
                    lst.remove(q)
        # module_funcs/class_alias key on (rel, name); the attr tables key
        # on ((rel, cls), attr) — filter each by ITS rel component
        for d in (self.module_funcs, self.class_alias):
            for k in [k for k in d if k[0] == rel]:
                del d[k]
        for d in (self.attr_types, self.attr_assign_calls, self.lock_attrs):
            for k in [k for k in d if k[0][0] == rel]:
                del d[k]
        self.module_traced = {k for k in self.module_traced if k[0] != rel}
        self.attr_traced = {k for k in self.attr_traced if k[0][0] != rel}
        self.classes = {k: v for k, v in self.classes.items() if k[0] != rel}
        self.module_of = {m: r for m, r in self.module_of.items()
                          if r != rel}
        self.signal_installs.pop(rel, None)
        self.thread_ctors.pop(rel, None)
        for lst_name in ("signal_handler_heads", "atexit_heads",
                         "hook_assign_heads", "jit_mark_heads",
                         "join_sites"):
            setattr(self, lst_name,
                    [t for t in getattr(self, lst_name)
                     if not t[0].startswith(rel + "::")])
        self.decorated_traced = {q for q in self.decorated_traced
                                 if not q.startswith(rel + "::")}
        self._version += 1

    def _module_name(self, rel: str) -> str:
        mod = rel[:-3] if rel.endswith(".py") else rel
        if mod.endswith("/__init__"):
            mod = mod[:-len("/__init__")]
        return mod.replace("/", ".")

    def _index_file(self, rel: str, tree: ast.AST) -> None:
        aliases = _import_aliases(tree)
        self.module_of[self._module_name(rel)] = rel
        quals: List[str] = []
        expr_calls: Set[int] = set()   # id(call) used as a bare statement
        for n in ast.walk(tree):
            if isinstance(n, ast.Expr) and isinstance(n.value, ast.Call):
                expr_calls.add(id(n.value))

        mod_node = FuncNode(f"{rel}::<module>", rel, "<module>", None,
                            None, 0, None, aliases)
        self.funcs[mod_node.qual] = mod_node
        quals.append(mod_node.qual)

        def visit_scope(owner: FuncNode, stmts, cls: Optional[tuple]):
            """Walk one runtime scope: nested defs become new nodes, class
            bodies recurse with the class key, everything else feeds the
            owner's call/assign records."""
            stack = list(stmts)
            while stack:
                s = stack.pop()
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._add_func(owner, s, cls, rel, aliases, quals,
                                   expr_calls)
                    continue
                if isinstance(s, ast.ClassDef):
                    clskey = (rel, s.name)
                    self.classes.setdefault(clskey, {})
                    self.class_alias[(rel, s.name)] = clskey
                    visit_scope(owner, s.body, clskey)
                    continue
                if isinstance(s, ast.Lambda):
                    continue
                self._record_stmt(owner, s, cls, expr_calls)
                stack.extend(ast.iter_child_nodes(s))

        visit_scope(mod_node, tree.body, None)
        self.file_quals[rel] = quals

    def _add_func(self, parent: FuncNode, fn, cls, rel, aliases, quals,
                  expr_calls) -> None:
        if parent.name == "<module>" and cls is None:
            qual = f"{rel}::{fn.name}"
        elif cls is not None and parent.name == "<module>":
            qual = f"{rel}::{cls[1]}.{fn.name}"
        else:
            qual = f"{parent.qual}.<locals>.{fn.name}"
        node = FuncNode(qual, rel, fn.name, cls, fn, fn.lineno,
                        parent, aliases)
        self.funcs[qual] = node
        quals.append(qual)
        parent.children[fn.name] = qual
        if cls is not None:
            self.classes.setdefault(cls, {})[fn.name] = qual
            self.methods_by_name.setdefault(fn.name, []).append(qual)
        elif parent.name == "<module>":
            self.module_funcs[(rel, fn.name)] = qual
        for d in fn.decorator_list:
            if self._deco_is_tracer(d):
                self.decorated_traced.add(qual)

        def visit(stmts, in_cls):
            stack = list(stmts)
            while stack:
                s = stack.pop()
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._add_func(node, s, in_cls, rel, aliases, quals,
                                   expr_calls)
                    continue
                if isinstance(s, ast.ClassDef):
                    clskey = (rel, f"{fn.name}.<locals>.{s.name}")
                    self.classes.setdefault(clskey, {})
                    visit(s.body, clskey)
                    continue
                if isinstance(s, ast.Lambda):
                    continue
                self._record_stmt(node, s, in_cls or cls, expr_calls)
                stack.extend(ast.iter_child_nodes(s))

        visit(fn.body, cls)
        # `return f` where f was bound to jit(...) earlier in the body
        for n2 in ast.walk(fn):
            if isinstance(n2, ast.Return) and isinstance(n2.value, ast.Name):
                if n2.value.id in node.local_traced:
                    node.returns_jit = True

    def _deco_is_tracer(self, d: ast.AST) -> bool:
        if isinstance(d, ast.Call):
            if terminal_name(d.func) == "partial":
                return any(self._deco_is_tracer(a) for a in d.args[:1])
            return self._deco_is_tracer(d.func)
        return _is_tracer_head(dotted_name(d) or terminal_name(d))

    def _record_stmt(self, node: FuncNode, s: ast.AST, cls, expr_calls):
        """Record the facts one (possibly nested) expression/statement
        contributes: calls, assignments, lock acquires, registrations."""
        if isinstance(s, ast.Call):
            head = dotted_name(s.func)
            node.calls.append((head, getattr(s, "lineno", 0)))
            resolved_head = _expand_alias(node.aliases, head)
            tname = terminal_name(s.func)
            # registrations whose argument is a callable reference
            if resolved_head == "signal.signal" and len(s.args) >= 2:
                h = dotted_name(s.args[1])
                if h:
                    self.signal_handler_heads.append((node.qual, h))
                self.signal_installs.setdefault(node.rel, []).append({
                    "qual": node.qual, "lineno": s.lineno,
                    "col": s.col_offset, "handler": s.args[1],
                    "result_used": id(s) not in expr_calls})
            elif resolved_head == "threading.Thread":
                kw = {k.arg: k.value for k in s.keywords}
                daemon = kw.get("daemon")
                target = kw.get("target")
                self.thread_ctors.setdefault(node.rel, []).append({
                    "qual": node.qual, "lineno": s.lineno,
                    "col": s.col_offset,
                    "daemon_true": isinstance(daemon, ast.Constant)
                    and daemon.value is True,
                    "target_head": dotted_name(target) if target else "",
                    "bind": self._pending_thread_binds.pop(
                        (node.rel, s.lineno), None)})
            elif resolved_head == "atexit.register" and s.args:
                h = dotted_name(s.args[0])
                if h:
                    self.atexit_heads.append((node.qual, h))
            elif tname in _TRACER_NAMES and s.args \
                    and isinstance(s.args[0], ast.Name):
                self.jit_mark_heads.append((node.qual, s.args[0].id))
            if tname == "acquire" and isinstance(s.func, ast.Attribute):
                self._record_lock_ref(node, s.func.value, s)
            if tname == "join" and isinstance(s.func, ast.Attribute):
                recv = terminal_name(s.func.value)
                if recv:
                    self.join_sites.append((node.qual, recv))
            # callable references escaping through arguments
            for a in list(s.args) + [k.value for k in s.keywords]:
                if isinstance(a, (ast.Name, ast.Attribute)):
                    h = dotted_name(a)
                    if h and h != "self":
                        node.arg_refs.append(h)
                elif isinstance(a, ast.Call):
                    h = dotted_name(a.func)
                    if h:
                        node.factory_args.append(h)
        elif isinstance(s, (ast.For, ast.While)):
            node.loops.append(s)
        elif isinstance(s, ast.With) or isinstance(s, ast.AsyncWith):
            for item in s.items:
                if isinstance(item.context_expr, (ast.Name, ast.Attribute)):
                    self._record_lock_ref(node, item.context_expr, s)
        elif isinstance(s, ast.Assign) and len(s.targets) == 1:
            self._record_assign(node, s.targets[0], s.value, cls)
        elif isinstance(s, ast.AnnAssign) and s.value is not None:
            # `self._lock: threading.Lock = threading.Lock()` must feed
            # lock_attrs/attr_types exactly like the unannotated form
            self._record_assign(node, s.target, s.value, cls)
        elif isinstance(s, ast.Return) and s.value is not None:
            if isinstance(s.value, ast.Call):
                head = dotted_name(s.value.func)
                node.return_calls.append(head)
                if _is_tracer_head(head):
                    node.returns_jit = True
                elif isinstance(s.value.func, ast.Name):
                    node.return_class = s.value.func.id

    def _record_lock_ref(self, node: FuncNode, expr: ast.AST, at) -> None:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            node.lock_acquires.append(("self", expr.attr, at.lineno,
                                       at.col_offset))
        elif isinstance(expr, ast.Name):
            node.lock_acquires.append(("name", expr.id, at.lineno,
                                       at.col_offset))

    def _record_assign(self, node: FuncNode, tgt, value, cls) -> None:
        is_self_attr = (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self" and cls is not None)
        # sys.excepthook = handler
        if isinstance(tgt, ast.Attribute) and dotted_name(tgt) in (
                "sys.excepthook", "threading.excepthook"):
            h = dotted_name(value)
            if h:
                self.hook_assign_heads.append((node.qual, h))
            return
        if not isinstance(value, ast.Call):
            return
        head = dotted_name(value.func)
        resolved = _expand_alias(node.aliases, head)
        lock_kind = {"threading.Lock": "Lock",
                     "threading.RLock": "RLock"}.get(resolved)
        traced = _is_tracer_head(head)
        clskey = self._class_for_ctor(node, head)
        # class-BODY statements are recorded on the enclosing module/
        # function node with ``cls`` set (node.cls != cls); a lock built
        # there (`_lock = threading.Lock()`) is still acquired via
        # ``self._lock``, so it must land in lock_attrs like the
        # __init__ form
        is_cls_body_name = (cls is not None and isinstance(tgt, ast.Name)
                            and node.cls != cls)
        if is_self_attr:
            key = (cls, tgt.attr)
            if lock_kind:
                self.lock_attrs[key] = lock_kind
            elif traced:
                self.attr_traced.add(key)
            elif clskey is not None:
                self.attr_types[key] = clskey
            else:
                self.attr_assign_calls[key] = head
        elif is_cls_body_name and lock_kind:
            self.lock_attrs[(cls, tgt.id)] = lock_kind
        elif isinstance(tgt, ast.Name):
            if traced:
                node.local_traced.add(tgt.id)
                if node.name == "<module>":
                    self.module_traced.add((node.rel, tgt.id))
            elif clskey is not None:
                node.local_types[tgt.id] = clskey
            else:
                node.local_assign_calls[tgt.id] = head
        # bind thread ctors to their assignment target (var or self attr)
        # so the DL103 join analysis can pair Thread() with .join() sites
        if resolved == "threading.Thread":
            bind = (tgt.attr if is_self_attr
                    else tgt.id if isinstance(tgt, ast.Name) else None)
            if bind:
                self._pending_thread_binds[(node.rel, value.lineno)] = bind

    def _class_for_ctor(self, node: FuncNode, head: str) -> Optional[tuple]:
        """(rel, clsname) when ``head`` is a constructor call of a project
        class — directly, via import alias, or dotted module path."""
        if "." not in head:
            ck = self.class_alias.get((node.rel, head))
            if ck is not None:
                return ck
            target = node.aliases.get(head)
            if target and "." in target:
                mod, _, name = target.rpartition(".")
                rel = self.module_of.get(mod)
                if rel is not None:
                    return self.class_alias.get((rel, name))
            return None
        mod, _, name = head.rpartition(".")
        mod = _expand_alias(node.aliases, mod)
        rel = self.module_of.get(mod)
        if rel is not None:
            return self.class_alias.get((rel, name))
        return None

    # -- resolution -----------------------------------------------------
    def _repo_tops(self) -> Set[str]:
        return {m.partition(".")[0] for m in self.module_of}

    def resolve(self, node: FuncNode, head: str) -> Tuple[tuple, bool]:
        """(target quals, dispatches_traced) for one dotted call head."""
        if not head:
            return ((), False)
        parts = head.split(".")
        if parts[0] == "":
            return ((), False)
        if len(parts) == 1:
            return self._resolve_bare(node, parts[0])
        if parts[0] in ("self", "cls") and node.cls is not None:
            out = self._resolve_typed(node.cls, parts[1:])
            if out is not None:
                return out
            return (self._fallback(parts[-1], node.rel), False)
        if parts[0] in node.local_types:
            out = self._resolve_typed(node.local_types[parts[0]], parts[1:])
            if out is not None:
                return out
            return (self._fallback(parts[-1], node.rel), False)
        # alias/module-dotted resolution
        target = node.aliases.get(parts[0])
        if target is not None:
            full = target + "." + ".".join(parts[1:])
            mod, _, fname = full.rpartition(".")
            rel = self.module_of.get(mod)
            if rel is not None:
                q = self.module_funcs.get((rel, fname))
                if q is not None:
                    return ((q,), q in self._jit_factories())
                ck = self.class_alias.get((rel, fname))
                if ck is not None:
                    init = self.classes.get(ck, {}).get("__init__")
                    return ((init,) if init else (), False)
            if full.partition(".")[0] not in self._repo_tops():
                return ((), False)   # external library: no fallback
        return (self._fallback(parts[-1], node.rel), False)

    def _resolve_bare(self, node: FuncNode, name: str) -> Tuple[tuple, bool]:
        cur = node
        while cur is not None:          # closures see enclosing defs
            if name in cur.children:
                return ((cur.children[name],), False)
            if name in cur.local_traced:
                return ((), True)
            ah = cur.local_assign_calls.get(name)
            if ah is not None:
                key = (id(cur), ah)
                if key not in self._resolving:
                    self._resolving.add(key)
                    try:
                        targets, _ = self.resolve(cur, ah)
                    finally:
                        self._resolving.discard(key)
                    if any(t in self._jit_factories() for t in targets):
                        return ((), True)   # var = make_step(...) -> traced
            cur = cur.parent
        q = self.module_funcs.get((node.rel, name))
        if q is not None:
            return ((q,), q in self._jit_factories())
        if (node.rel, name) in self.module_traced:
            return ((), True)
        target = node.aliases.get(name)
        if target is not None:
            if "." in target:
                mod, _, fname = target.rpartition(".")
                rel = self.module_of.get(mod)
                if rel is not None:
                    q = self.module_funcs.get((rel, fname))
                    if q is not None:
                        return ((q,), q in self._jit_factories())
                    ck = self.class_alias.get((rel, fname))
                    if ck is not None:
                        init = self.classes.get(ck, {}).get("__init__")
                        return ((init,) if init else (), False)
        return ((), False)

    def _resolve_typed(self, clskey: tuple,
                       parts: Sequence[str]) -> Optional[Tuple[tuple, bool]]:
        cur = clskey
        for a in parts[:-1]:
            nxt = self.attr_types.get((cur, a))
            if nxt is None:
                ah = self.attr_assign_calls.get((cur, a))
                if ah is not None:
                    # one-hop return-type inference: factory returning a
                    # direct constructor call (serve_metrics -> MetricsServer)
                    for q in self._heads_to_quals(cur, ah):
                        rc = self.funcs[q].return_class
                        if rc is not None:
                            ck = self.class_alias.get((self.funcs[q].rel, rc))
                            if ck is not None:
                                nxt = ck
                                break
            if nxt is None:
                return None
            cur = nxt
        m = parts[-1]
        q = self.classes.get(cur, {}).get(m)
        if q is not None:
            return ((q,), False)
        if (cur, m) in self.attr_traced:
            return ((), True)
        ah = self.attr_assign_calls.get((cur, m))
        if ah is not None:
            # self.train_step = make_train_step(...): traced handle when the
            # maker is (transitively) a jit factory
            owner_rel = cur[0]
            mod_node = self.funcs.get(f"{owner_rel}::<module>")
            base = mod_node if mod_node is not None else None
            if base is not None:
                targets, traced = self.resolve(base, ah)
                if traced or any(t in self._jit_factories()
                                 for t in targets):
                    return ((), True)
        return None

    def _heads_to_quals(self, clskey, head) -> tuple:
        rel = clskey[0]
        mod_node = self.funcs.get(f"{rel}::<module>")
        if mod_node is None:
            return ()
        targets, _ = self.resolve(mod_node, head)
        return targets

    def _fallback(self, name: str, from_rel: Optional[str] = None) -> tuple:
        if name in _FALLBACK_NOISE or name.startswith("__"):
            return ()
        out = self.methods_by_name.get(name, ())
        # deterministic under overlays: only the overlay file itself may
        # fallback-resolve into its own methods
        return tuple(q for q in out
                     if (rel := q.partition("::")[0]) == from_rel
                     or rel not in self.overlay_files)

    # -- derived sets (memoized per version) ----------------------------
    def _memoized(self, key: str, compute):
        hit = self._memo.get(key)
        if hit is not None and hit[0] == self._version:
            return hit[1]
        val = compute()
        self._memo[key] = (self._version, val)
        return val

    def _jit_factories(self) -> Set[str]:
        def compute():
            # fixpoint WITHOUT resolve() (resolve consults this set):
            # direct `return jit(...)` seeds, then one name-resolution
            # round per iteration for `return make_inner(...)` chains
            fac = {q for q, n in self.funcs.items() if n.returns_jit}
            changed = True
            while changed:
                changed = False
                for q, n in self.funcs.items():
                    if q in fac:
                        continue
                    for rc in n.return_calls:
                        if "." in rc:
                            continue
                        tq = self.module_funcs.get((n.rel, rc))
                        if tq is None and n.parent is not None:
                            tq = n.parent.children.get(rc)
                        if tq is None:
                            # cross-module factory chain through an import
                            # alias (`from plan.compile import
                            # compile_train_step` inside the shim body):
                            # the make_* builders return the plan
                            # compiler's product since round 15, so the
                            # chain must survive the module boundary —
                            # a plain table lookup, no resolve() recursion
                            target = n.aliases.get(rc)
                            if target and "." in target:
                                mod, _, fname = target.rpartition(".")
                                rel = self.module_of.get(mod)
                                if rel is not None:
                                    tq = self.module_funcs.get((rel, fname))
                        if tq in fac:
                            fac.add(q)
                            changed = True
                            break
            return fac
        return self._memoized("jit_factories", compute)

    def traced_funcs(self) -> Set[str]:
        """Functions whose BODY is jit/shard_map-traced: decorated, passed
        to jit(f), or defined inside a jit factory (the step closures)."""
        def compute():
            out = set(self.decorated_traced)
            for qual, name in self.jit_mark_heads:
                n = self.funcs.get(qual)
                if n is not None:
                    targets, _ = self._resolve_bare(n, name)
                    out.update(targets)
            for fq in self._jit_factories():
                n = self.funcs.get(fq)
                if n is not None:
                    out.update(n.children.values())
            return out
        return self._memoized("traced", compute)

    def edges(self, qual: str) -> Tuple[tuple, bool]:
        """(resolved same-scope callee quals, dispatches_traced)."""
        hit = self._edges.get(qual)
        if hit is not None:
            return hit
        n = self.funcs.get(qual)
        if n is None:
            return ((), False)
        targets: List[str] = []
        traced = False
        for head, _ in n.calls:
            t, tr = self.resolve(n, head)
            targets.extend(t)
            traced = traced or tr
        out = (tuple(dict.fromkeys(targets)), traced)
        self._edges[qual] = out
        return out

    def reachable_from(self, roots: Iterable[str]) -> Set[str]:
        """Forward closure over call edges (cycle-tolerant BFS)."""
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.funcs]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            for t in self.edges(q)[0]:
                if t not in seen:
                    stack.append(t)
        return seen

    def _heads_set(self, pairs: List[Tuple[str, str]]) -> Set[str]:
        out: Set[str] = set()
        for qual, head in pairs:
            n = self.funcs.get(qual)
            if n is None:
                continue
            targets, _ = self.resolve(n, head)
            out.update(targets)
        return out

    def signal_handlers(self) -> Set[str]:
        return self._memoized(
            "sig", lambda: self._heads_set(self.signal_handler_heads))

    def atexit_hooks(self) -> Set[str]:
        return self._memoized(
            "atexit", lambda: self._heads_set(self.atexit_heads))

    def hook_assigns(self) -> Set[str]:
        return self._memoized(
            "hooks", lambda: self._heads_set(self.hook_assign_heads))

    def thread_targets(self) -> Set[str]:
        def compute():
            pairs = []
            for rel, recs in self.thread_ctors.items():
                for r in recs:
                    if r["target_head"]:
                        pairs.append((r["qual"], r["target_head"]))
            return self._heads_set(pairs)
        return self._memoized("threads", compute)

    def escaped_callbacks(self) -> Set[str]:
        """Functions whose references escape through call arguments (sink
        registrations etc.) plus closures returned by factories whose
        results are passed along — conservatively callable from the main
        line of execution."""
        def compute():
            out: Set[str] = set()
            for n in self.funcs.values():
                for h in n.arg_refs:
                    targets, _ = self.resolve(n, h)
                    out.update(targets)
                for h in n.factory_args:
                    targets, _ = self.resolve(n, h)
                    for t in targets:
                        tn = self.funcs.get(t)
                        if tn is not None:
                            out.update(tn.children.values())
            return out
        return self._memoized("escaped", compute)

    def handler_reachable(self) -> Set[str]:
        return self._memoized(
            "hreach", lambda: self.reachable_from(self.signal_handlers()))

    def mainline_reachable(self) -> Set[str]:
        """Reachable from non-signal entry points: module-level code,
        thread targets, atexit/excepthook hooks, and escaped callbacks."""
        def compute():
            roots = {q for q in self.funcs if q.endswith("::<module>")}
            roots |= self.thread_targets() | self.atexit_hooks()
            roots |= self.hook_assigns() | self.escaped_callbacks()
            return self.reachable_from(roots)
        return self._memoized("mreach", compute)

    def shutdown_reachable(self) -> Set[str]:
        """Reachable from the run-teardown surface (DL103's join check):
        atexit hooks, signal handlers, excepthooks, and methods
        conventionally on the shutdown path."""
        def compute():
            roots = (self.atexit_hooks() | self.signal_handlers()
                     | self.hook_assigns())
            for q, n in self.funcs.items():
                if n.name in ("close", "stop", "shutdown", "run_end",
                              "__exit__", "__del__"):
                    roots.add(q)
            return self.reachable_from(roots)
        return self._memoized("shutdown", compute)

    def file_nodes(self, rel: str) -> List[FuncNode]:
        """The FuncNodes of one indexed file (module pseudo-node first)."""
        return [self.funcs[q] for q in self.file_quals.get(rel, ())
                if q in self.funcs]

    def reaches_traced(self) -> Set[str]:
        """Functions from which a traced (jit) dispatch is reachable —
        the 'this code drives the device' closure DL002 derives hot loops
        from."""
        def compute():
            rev: Dict[str, List[str]] = {}
            seeds: List[str] = []
            for q in self.funcs:
                targets, traced = self.edges(q)
                if traced:
                    seeds.append(q)
                for t in targets:
                    rev.setdefault(t, []).append(q)
            seen: Set[str] = set()
            stack = list(seeds)
            while stack:
                q = stack.pop()
                if q in seen:
                    continue
                seen.add(q)
                stack.extend(rev.get(q, ()))
            return seen
        return self._memoized("reaches_traced", compute)


class graph_scope:
    """Context manager giving a rule the project graph WITH the current
    file indexed. Out-of-surface files (fixtures, tmp snippets) are
    removed again on exit so one test's deliberately-bad code never
    leaks roots into another's reachability queries."""

    def __init__(self, project: Project, ctx: "FileContext"):
        self._graph = project.callgraph
        self._ctx = ctx
        self._added = False

    def __enter__(self) -> CallGraph:
        self._added = self._graph.ensure_file(self._ctx.rel,
                                              tree=self._ctx.tree,
                                              path=self._ctx.path,
                                              src=self._ctx.src)
        return self._graph

    def __exit__(self, *exc) -> None:
        if self._added:
            self._graph.remove_file(self._ctx.rel)


_GRAPH_CACHE: Dict[str, CallGraph] = {}


def load_callgraph(root: str = REPO_ROOT) -> CallGraph:
    """Process-wide cached call graph over :data:`GRAPH_SURFACE` (the
    build parses every surface file once; ~100ms-scale, amortized across
    every rule and every test in the process)."""
    root = os.path.abspath(root)
    g = _GRAPH_CACHE.get(root)
    if g is None:
        g = CallGraph(root)
        present = [p for p in GRAPH_SURFACE
                   if os.path.exists(os.path.join(root, p))]
        if present:
            for path in iter_python_files(present, root):
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                g.ensure_file(rel, path=path)
        g._base_built = True   # everything added from here on is overlay
        _GRAPH_CACHE[root] = g
    return g


# ----------------------------------------------------------- ast helpers
def dotted_name(node: ast.AST) -> str:
    """Dotted receiver chain: ``jax.random.split`` -> 'jax.random.split',
    ``self.obs.ledger`` -> 'self.obs.ledger'. Non-name roots (calls,
    subscripts) contribute an empty head: ``foo().bar`` -> '.bar'."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    parts.append(node.id if isinstance(node, ast.Name) else "")
    return ".".join(reversed(parts))


def terminal_name(node: ast.AST) -> str:
    """The final component of a name/attribute chain ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""
