#!/usr/bin/env python3
"""Per-request waterfalls, tail-latency attribution, SLO-breach exemplars.

The request observatory's reading side (obs.reqtrace is the writing
side): point it at one serving ledger or a fleet directory and it answers
"where did THIS request's latency go" —

* **waterfalls**: the span tree of a trace rendered as per-phase bars,
  cross-host traces showing every host-attempt that touched the rid;
* **tail attribution**: each completed request's admit->finish latency
  decomposed into the named categories (queue / prefill / decode) with a
  goodput-style sum-check — attributed seconds + residue == measured
  latency, per request — and the TTFT/TPOT percentiles decomposed by
  their nearest-rank exemplar request, so "p99 TTFT is queue" is a
  statement about a concrete rid, not a vibe;
* **exemplar index**: every ``slo`` breach event bound to the concrete
  worst-offender traces inside its breach window (wall-clock emit
  timestamps — the one clock comparable across hosts), so a breach is a
  link to evidence, not just a counter bump.

Usage::

    python tools/request_report.py out/serve.jsonl
    python tools/request_report.py out/fleet_dir --json
    python tools/request_report.py out/fleet_dir --waterfalls 5

Stdlib-only and deterministic: the same ledger bytes produce the same
report bytes (scripts/lint.sh gates on it).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tpu_dist.obs import reqtrace                              # noqa: E402
from tpu_dist.obs.goodput import load_job_records              # noqa: E402

# per-request sum-check tolerance: span endpoints are rounded to 1e-6
# before emit, so a request's tiling can drift by ~n_spans * 0.5e-6 —
# 1e-4 passes every honest ledger and still catches a lost span window
SUM_TOL = 1e-4
# exemplar window around a breach's wall timestamp: spans admitted during
# the breach close (and emit) shortly AFTER the slo record, sheds shortly
# before — symmetric slack covers both without reaching across the run
EXEMPLAR_WINDOW_S = 30.0
EXEMPLARS_PER_BREACH = 3
_BAR_W = 32

LABELS = {
    "queue": "admission backlog (queue span: submit -> prefill start)",
    "prefill": "prompt processing (bucket pad, page writes, first token)",
    "decode": "token generation (windowed decode ticks, spec rounds)",
    "residue": "unattributed (lost spans / torn ledger)",
}


def _pctl(xs: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of a sorted list (the repo convention —
    tools/ledger_report._pctl; local copy keeps this tool import-light)."""
    if not xs:
        return None
    return xs[min(int(round(q / 100.0 * (len(xs) - 1))), len(xs) - 1)]


def _dur(span: dict) -> float:
    return float(span.get("end") or 0.0) - float(span.get("start") or 0.0)


# -- attribution ------------------------------------------------------------

def attribute_root(root: dict, trace: dict) -> dict:
    """One completed host-attempt view of a request, decomposed: category
    seconds summed from the root's child spans, residue = measured
    latency minus attributed. The ``queue``/``prefill``/``decode`` spans
    tile admit->finish by construction (engine.serve), so residue ~ 0 on
    a healthy ledger and the sum-check is an identity that a LOST span
    breaks — exactly the goodput ``sum_check`` discipline per request."""
    kids = [s for s in trace["spans"]
            if s.get("parent_id") == root["span_id"]]
    cats = {c: 0.0 for c in reqtrace.CATEGORIES}
    for s in kids:
        if s.get("name") in cats:
            cats[s["name"]] += _dur(s)
    latency = _dur(root)
    attributed = sum(cats.values())
    residue = latency - attributed
    tokens = root.get("tokens")
    decode_s = cats["decode"]
    row = {
        "trace_id": trace["trace_id"], "rid": root.get("rid"),
        "job_id": root.get("job_id"), "attempt": root.get("attempt"),
        "host": root.get("host"),
        "latency_s": round(latency, 6),
        "queue_s": round(cats["queue"], 6),
        "prefill_s": round(cats["prefill"], 6),
        "decode_s": round(decode_s, 6),
        "residue_s": round(residue, 6),
        "ttft_s": root.get("ttft_s"),
        "tokens": tokens,
        "tpot_s": (round(decode_s / tokens, 6) if tokens else None),
        "spans": len(kids),
        "sum_check_ok": abs(residue) <= SUM_TOL,
        "ts": root.get("ts"),
    }
    return row


def request_rows(traces: Dict[str, dict]) -> List[dict]:
    """Every completed (root-emitting) host-attempt of every trace, in a
    deterministic order: by rid, then job identity."""
    rows = []
    for tid in sorted(traces):
        tr = traces[tid]
        for root in tr["roots"]:
            rows.append(attribute_root(root, tr))
    rows.sort(key=lambda r: (r["rid"] if r["rid"] is not None else -1,
                             str(r["job_id"]), r["attempt"] or 0))
    return rows


def _tail_point(rows: List[dict], metric: str, parts) -> Dict[str, dict]:
    """p50/p90/p99 of ``metric`` with the nearest-rank request's named
    decomposition attached — the percentile IS a concrete request here,
    so its split is an attribution, not an average that matches nobody."""
    pool = sorted((r for r in rows if r.get(metric) is not None),
                  key=lambda r: (r[metric], str(r["trace_id"])))
    out = {}
    for q in (50, 90, 99):
        r = _pctl(pool, q)
        if r is None:
            out[f"p{q}"] = None
            continue
        out[f"p{q}"] = {metric: r[metric], "rid": r["rid"],
                        "trace_id": r["trace_id"],
                        **{p: r[p] for p in parts}}
    return out


def tail_attribution(rows: List[dict]) -> dict:
    """The headline block: TTFT decomposes into queue+prefill, TPOT into
    decode-per-token; ``shares`` are the fleet-wide category fractions of
    total latency; ``coverage`` the attributed share (1.0 minus residue)
    — the bench_track-gated number, ~1.0 by construction on any ledger
    that didn't lose spans."""
    total = sum(r["latency_s"] for r in rows)
    shares = {}
    for cat in (*reqtrace.CATEGORIES, "residue"):
        secs = sum(r[f"{cat}_s"] for r in rows)
        shares[cat] = {"seconds": round(secs, 6),
                       "share": round(secs / total, 6) if total else None,
                       "label": LABELS[cat]}
    attributed = sum(shares[c]["seconds"] for c in reqtrace.CATEGORIES)
    return {
        "requests": len(rows),
        "ttft": _tail_point(rows, "ttft_s", ("queue_s", "prefill_s")),
        "tpot": _tail_point(rows, "tpot_s", ("decode_s", "tokens")),
        "shares": shares,
        "coverage": round(attributed / total, 6) if total else None,
        "sum_check": {
            "ok": all(r["sum_check_ok"] for r in rows),
            "requests": len(rows),
            "failed": [r["trace_id"] for r in rows
                       if not r["sum_check_ok"]],
            "max_residue_s": (round(max(abs(r["residue_s"]) for r in rows),
                                    6) if rows else 0.0),
            "tolerance_s": SUM_TOL,
        },
    }


# -- SLO-breach exemplars ---------------------------------------------------

def _candidates(records, traces: Dict[str, dict]) -> List[dict]:
    """Everything a breach can point at: completed request roots (scored
    by their category seconds) and shed spans (a shed IS the overload's
    victim). Wall ``ts`` (emit time) is the clock — the only one
    comparable to the slo record's own stamp."""
    out = []
    for tid in sorted(traces):
        tr = traces[tid]
        for root in tr["roots"]:
            row = attribute_root(root, tr)
            if row["ts"] is not None:
                out.append({"kind": "request", **row})
        for s in tr["spans"]:
            if s.get("name") == "shed" and s.get("ts") is not None:
                out.append({"kind": "shed", "trace_id": tid,
                            "rid": s.get("rid"), "host": s.get("host"),
                            "job_id": s.get("job_id"),
                            "queue_s": round(_dur(s), 6),
                            "latency_s": round(_dur(s), 6),
                            "reason": s.get("reason"), "ts": s["ts"]})
    return out


def slo_exemplars(records, traces: Dict[str, dict]) -> List[dict]:
    """Bind every ``slo`` breach event to its worst-offender traces: the
    top candidates by the breach-relevant score (queue seconds for
    queue_wait breaches, whole latency otherwise) inside the wall-clock
    breach window, same host first. A breach with an empty window falls
    back to the nearest candidate in time — a breach that resolves to NO
    evidence is a report bug, not a tolerable gap (the fleet_ci
    acceptance asserts >= 1 exemplar per breach)."""
    cands = _candidates(records, traces)
    out = []
    for rec in records:
        if rec.get("event") != "slo" or rec.get("ts") is None:
            continue
        kind = rec.get("kind")
        score_key = "queue_s" if kind == "queue_wait" else "latency_s"
        host = rec.get("host")
        same_host = [c for c in cands
                     if host is None or c.get("host") == host]
        pool = same_host or cands
        windowed = [c for c in pool
                    if abs(c["ts"] - rec["ts"]) <= EXEMPLAR_WINDOW_S]
        chosen = sorted(
            windowed,
            key=lambda c: (-(c.get(score_key) or 0.0),
                           str(c["trace_id"])))[:EXEMPLARS_PER_BREACH]
        if not chosen and pool:
            chosen = sorted(
                pool, key=lambda c: (abs(c["ts"] - rec["ts"]),
                                     str(c["trace_id"])))[:1]
        out.append({
            "kind": kind, "host": host, "value": rec.get("value"),
            "floor": rec.get("floor"), "step": rec.get("step"),
            "exemplars": [
                {"trace_id": c["trace_id"], "rid": c["rid"],
                 "kind": c["kind"], "job_id": c.get("job_id"),
                 "score_s": round(c.get(score_key) or 0.0, 6),
                 "dt_s": round(c["ts"] - rec["ts"], 3)}
                for c in chosen],
        })
    return out


# -- waterfalls -------------------------------------------------------------

def waterfall_lines(trace: dict) -> List[str]:
    """One trace as indented bars. Each host-attempt renders against its
    OWN engine clock (per-process axes don't compare); the trace header
    carries the cross-host identity that ties them together."""
    rows = [attribute_root(root, trace) for root in trace["roots"]]
    latency = max((r["latency_s"] for r in rows), default=0.0)
    hosts = ",".join(str(h) for h in trace["hosts"]) or "-"
    lines = [f"trace {trace['trace_id']}  rid={trace['rid']}  "
             f"hosts=[{hosts}]  attempts={len(trace['roots'])}  "
             f"latency={latency:.6g}s"]
    by_parent = reqtrace.children_of(trace)
    orphans = [s for s in trace["spans"]
               if s.get("parent_id") is not None
               and s["parent_id"] not in {r["span_id"]
                                          for r in trace["roots"]}]
    for root in trace["roots"]:
        t0, t1 = float(root["start"]), float(root["end"])
        width = max(t1 - t0, 1e-9)
        lines.append(f"  [{root.get('job_id')} a{root.get('attempt')}] "
                     f"request {t0:.6g} -> {t1:.6g}  ({t1 - t0:.6g}s)")
        for s in by_parent.get(root["span_id"], ()):
            off = int(_BAR_W * (float(s["start"]) - t0) / width)
            n = max(int(_BAR_W * _dur(s) / width), 1)
            off = min(off, _BAR_W - 1)
            n = min(n, _BAR_W - off)
            bar = "." * off + "#" * n + "." * (_BAR_W - off - n)
            extra = ""
            if s.get("name") == "prefill":
                extra = (f"  bucket={s.get('bucket')} "
                         f"shared={s.get('pages_shared')}")
            elif s.get("name") == "decode":
                extra = (f"  ticks={s.get('ticks')} "
                         f"tokens={s.get('tokens')}")
            elif s.get("name") in ("shed", "readmit"):
                extra = f"  reason={s.get('reason')}"
            lines.append(f"    {s.get('name'):<10} |{bar}| "
                         f"{_dur(s):.6g}s{extra}")
    for s in orphans:
        lines.append(f"  [{s.get('job_id')} a{s.get('attempt')}] "
                     f"{s.get('name'):<10} (no root: attempt never "
                     f"completed it)  {_dur(s):.6g}s  "
                     f"reason={s.get('reason')}")
    return lines


def slowest_traces(traces: Dict[str, dict], n: int) -> List[dict]:
    """The n slowest traces by their worst completed attempt, slowest
    first (trace_id tie-break keeps the order reproducible)."""
    scored = []
    for tid in sorted(traces):
        tr = traces[tid]
        if not tr["roots"]:
            continue
        worst = max(_dur(r) for r in tr["roots"])
        scored.append((worst, tid, tr))
    scored.sort(key=lambda x: (-x[0], x[1]))
    return [tr for _w, _tid, tr in scored[:n]]


# -- the report -------------------------------------------------------------

def requests_summary(records) -> dict:
    """The one machine-readable dict (``--json`` prints it verbatim; the
    fleet_ci acceptance asserts into it)."""
    traces = reqtrace.traces(records)
    rows = request_rows(traces)
    sheds = sum(1 for t in traces.values()
                for s in t["spans"] if s.get("name") == "shed")
    readmits = sum(1 for t in traces.values()
                   for s in t["spans"] if s.get("name") == "readmit")
    return {
        "traces": len(traces),
        "completed_requests": len(rows),
        "cross_host_traces": sum(1 for t in traces.values()
                                 if len(t["hosts"]) > 1),
        "sheds": sheds,
        "readmits": readmits,
        "per_request": rows,
        "tail_attribution": tail_attribution(rows) if rows else None,
        "slo_exemplars": slo_exemplars(records, traces),
        "slowest": [t["trace_id"] for t in slowest_traces(traces, 5)],
    }


def render(summary: dict, records, out=print, waterfalls: int = 3) -> None:
    out("== requests (per-request traces: obs.reqtrace) ==")
    out(f"  traces {summary['traces']}  completed "
        f"{summary['completed_requests']}  cross-host "
        f"{summary['cross_host_traces']}  sheds {summary['sheds']}  "
        f"readmits {summary['readmits']}")
    ta = summary.get("tail_attribution")
    if ta:
        sc = ta["sum_check"]
        out(f"  sum-check: {'OK' if sc['ok'] else 'FAILED'} over "
            f"{sc['requests']} requests (max residue "
            f"{sc['max_residue_s']:.6g}s, tol {sc['tolerance_s']:g})")
        out(f"  coverage: {ta['coverage']} of latency attributed")
        out("  where the seconds went:")
        for cat, row in ta["shares"].items():
            share = "-" if row["share"] is None else f"{row['share']:.1%}"
            out(f"    {cat:<8} {row['seconds']:>10.6g}s  {share:>7}  "
                f"{row['label']}")
        for metric, parts in (("ttft", ("queue_s", "prefill_s")),
                              ("tpot", ("decode_s", "tokens"))):
            out(f"  {metric} percentiles (nearest-rank exemplar request):")
            for q in ("p50", "p90", "p99"):
                p = ta[metric][q]
                if p is None:
                    out(f"    {q}: no data")
                    continue
                split = "  ".join(f"{k}={p[k]}" for k in parts)
                out(f"    {q}: {p[metric + '_s']:.6g}s  rid={p['rid']}  "
                    f"{split}")
    if summary["slo_exemplars"]:
        out("  slo breaches -> exemplar traces:")
        for b in summary["slo_exemplars"]:
            host = "-" if b["host"] is None else b["host"]
            ex = ", ".join(
                f"rid={e['rid']} {e['kind']} {e['score_s']:.6g}s "
                f"({e['trace_id'][:8]})" for e in b["exemplars"]) or "NONE"
            out(f"    [{b['kind']} host={host} value={b['value']}] {ex}")
    if waterfalls > 0:
        traces = reqtrace.traces(records)
        slow = slowest_traces(traces, waterfalls)
        if slow:
            out(f"  {len(slow)} slowest request waterfalls:")
            for tr in slow:
                for line in waterfall_lines(tr):
                    out("    " + line)


def load_records(path: str, discover: bool = True) -> List[dict]:
    """A ledger file loads as one job (attempt family + sup sibling); a
    directory loads as a fleet (host*/ subtrees, host stamped on every
    record — the cross-host exemplar index needs it)."""
    if os.path.isdir(path):
        from tpu_dist.sim.fleet import FleetLedger

        return FleetLedger.discover(path).merged()
    return load_job_records(path, discover=discover)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-request waterfalls, tail-latency attribution "
                    "and SLO-breach exemplars from span ledger events")
    ap.add_argument("path", help="serving ledger (.jsonl) or fleet dir")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the machine-readable summary")
    ap.add_argument("--waterfalls", type=int, default=3,
                    help="N slowest request waterfalls in human output")
    ap.add_argument("--no-discover", action="store_true",
                    help="read exactly this file, no attempt-family glob")
    args = ap.parse_args(argv)
    records = load_records(args.path, discover=not args.no_discover)
    summary = requests_summary(records)
    if args.as_json:
        print(json.dumps(summary, default=str))
    else:
        render(summary, records, out=print, waterfalls=args.waterfalls)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
