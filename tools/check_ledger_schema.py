#!/usr/bin/env python
"""Thin shim over distlint rule DL006 (the ledger-schema check's new home).

The original AST walker grew into ``tools/distlint`` — a whole-tree
SPMD-correctness linter — and this check became its rule DL006, so there
is exactly ONE AST walker to maintain. This entry point stays for
callers/CI muscle memory and keeps the original API surface:

* :func:`load_schema` — EVENT_SCHEMA extracted from ledger.py by AST;
* :func:`check_file` — one file's violations as ``rel:line: msg`` strings;
* :func:`check_tree` — the historical sweep (tpu_dist, tools, tests,
  scripts, bench.py), same string format;
* CLI: ``python tools/check_ledger_schema.py [root]`` — prints violations,
  exits non-zero if any.

``# ledger-schema: forward`` on a call line still declares a forwarding
wrapper (distlint's DL006 honors it), and ``# distlint: disable=DL006 --
reason`` now works too.
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:  # script invocation: make 'tools.distlint' importable
    sys.path.insert(0, ROOT)

from tools.distlint.core import (FileContext, load_event_schema,  # noqa: E402
                                 lint_files, parse_suppressions)
from tools.distlint.rules import check_emit_calls  # noqa: E402

SCHEMA_FILE = os.path.join("tpu_dist", "obs", "ledger.py")
CHECKED = ("tpu_dist", "tools", "tests", "scripts")
CHECKED_FILES = ("bench.py",)
FORWARD_MARK = "ledger-schema: forward"


def load_schema(root: str = ROOT) -> dict:
    return load_event_schema(root)


def check_file(path: str, schema: dict, rel: str) -> list:
    """One file's DL006 violations in the historical string format.
    Honors the same suppressions as the lint gate (`# distlint:
    disable=DL006 -- reason`), so the two API surfaces always agree."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        ctx = FileContext(path, rel, src)
    except SyntaxError as e:
        return [f"{rel}: unparseable ({e})"]
    sups, _ = parse_suppressions(src)
    suppressed = {s.line for s in sups if "DL006" in s.rules}
    return [f"{f.path}:{f.line}: {f.message}"
            for f in check_emit_calls(ctx, schema)
            if f.line not in suppressed]


def check_tree(root: str = ROOT) -> list:
    """The historical sweep, now one distlint invocation (DL006 only;
    distlint's walker skips fixture dirs, where deliberately bad emit
    calls live as linter test data)."""
    paths = [d for d in CHECKED if os.path.isdir(os.path.join(root, d))]
    paths += [f for f in CHECKED_FILES
              if os.path.exists(os.path.join(root, f))]
    result = lint_files(paths, root=root, select=["DL006"])
    return [f"{f.path}:{f.line}: {f.message}" for f in result.findings]


def main(argv=None) -> int:
    root = (argv or sys.argv[1:] or [ROOT])[0]
    violations = check_tree(root)
    for v in violations:
        print(v, file=sys.stderr)
    print(f"check_ledger_schema: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
