#!/usr/bin/env python
"""Static ledger-schema check: every ``*.emit(...)`` call site conforms.

Walks the tree's Python ASTs (no imports of jax — or of anything else from
the checked modules: the schema itself is extracted from
``tpu_dist/obs/ledger.py`` by AST too) and verifies, for every call of the
form ``<something named ...ledger...>.emit(...)``:

* the event name is a LITERAL string naming a declared ``EVENT_SCHEMA``
  event (a computed event name defeats static checking — declare a new
  event instead);
* every required field of that event appears as an explicit keyword (a
  bare ``**fields`` splat hides required fields from the checker, so only
  the NON-required extras may ride in a splat — except for forwarding
  wrappers that re-expose ``emit``'s own signature, which declare
  themselves via a ``# ledger-schema: forward`` comment on the call line).

Wired into tier-1 as a plain test (tests/test_obs.py) so schema drift —
a renamed field, an undeclared event — fails fast at review time, not at
3am when someone greps a ledger.

CLI: ``python tools/check_ledger_schema.py [root]`` — prints violations,
exits non-zero if any.
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA_FILE = os.path.join("tpu_dist", "obs", "ledger.py")
# directories whose .py files are checked (tests included: a test emitting
# a drifted record would otherwise pin the drift as "expected")
CHECKED = ("tpu_dist", "tools", "tests", "scripts")
CHECKED_FILES = ("bench.py",)
FORWARD_MARK = "ledger-schema: forward"


def load_schema(root: str = ROOT) -> dict:
    """EVENT_SCHEMA extracted from ledger.py source by AST — the dict is a
    pure literal by contract (see its definition comment)."""
    src = open(os.path.join(root, SCHEMA_FILE)).read()
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "EVENT_SCHEMA":
                    return ast.literal_eval(node.value)
    raise AssertionError(f"EVENT_SCHEMA literal not found in {SCHEMA_FILE}")


def _terminal_name(func_value) -> str:
    """The receiver's final name: ``self.obs.ledger`` -> 'ledger',
    ``led`` -> 'led'."""
    node = func_value
    while isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_ledger_emit(call: ast.Call) -> bool:
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "emit"):
        return False
    name = _terminal_name(f.value).lower()
    # 'led' included: the natural short name must not dodge the checker
    return "ledger" in name or name == "led"


def check_file(path: str, schema: dict, rel: str) -> list:
    src = open(path).read()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [f"{rel}: unparseable ({e})"]
    lines = src.splitlines()
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_ledger_emit(node)):
            continue
        where = f"{rel}:{node.lineno}"
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if FORWARD_MARK in line:
            continue  # declared forwarding wrapper (re-exposes emit())
        if not node.args:
            out.append(f"{where}: emit() without an event argument")
            continue
        ev = node.args[0]
        if not (isinstance(ev, ast.Constant) and isinstance(ev.value, str)):
            out.append(f"{where}: event name must be a literal string "
                       "(static checkability)")
            continue
        required = schema.get(ev.value)
        if required is None:
            out.append(f"{where}: undeclared event {ev.value!r} "
                       f"(EVENT_SCHEMA: {sorted(schema)})")
            continue
        kw = {k.arg for k in node.keywords if k.arg is not None}
        missing = [f for f in required if f not in kw]
        if missing:
            out.append(f"{where}: event {ev.value!r} missing required "
                       f"keyword(s) {missing}")
    return out


def check_tree(root: str = ROOT) -> list:
    schema = load_schema(root)
    violations = []
    targets = []
    for d in CHECKED:
        for dirpath, _, files in os.walk(os.path.join(root, d)):
            targets += [os.path.join(dirpath, f) for f in files
                        if f.endswith(".py")]
    targets += [os.path.join(root, f) for f in CHECKED_FILES]
    for path in sorted(targets):
        if not os.path.exists(path):
            continue
        rel = os.path.relpath(path, root)
        violations += check_file(path, schema, rel)
    return violations


def main(argv=None) -> int:
    root = (argv or sys.argv[1:] or [ROOT])[0]
    violations = check_tree(root)
    for v in violations:
        print(v, file=sys.stderr)
    print(f"check_ledger_schema: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
