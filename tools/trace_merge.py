#!/usr/bin/env python
"""Merge a run's per-process ledgers into one Chrome/Perfetto trace.

    python tools/trace_merge.py run.jsonl                 # + run.p*.jsonl
    python tools/trace_merge.py run.jsonl -o trace.json
    python tools/trace_merge.py a.jsonl b.p1.jsonl --no-discover

A multi-host run writes one ledger per process (``run.jsonl``,
``run.p1.jsonl``, ... — obs.ledger.per_process_path); each file is a
correct per-process timeline, but a straggler or a lopsided eval only
shows when the lanes sit side by side. This tool merges every sibling
ledger into one ``trace.json`` in the Chrome trace-event format, loadable
in ``chrome://tracing`` / https://ui.perfetto.dev:

* one **process lane per ledger** (pid = the ledger's process index),
  with named thread rows: ``steps`` (the data/dispatch/device slices of
  every step record, laid back-to-back ending at the record's emit time,
  plus decode calls), ``comm`` (the overlapped comm_s share beside its
  device slice), ``phases`` (epoch spans, eval/ckpt markers) and
  ``alerts`` (watchdog stalls, health trips);
* **counter tracks** for skew spread and HBM-in-use, so a straggler
  reads as a rising curve, not a grep;
* clocks are normalized per process to its own ``run_start`` timestamp
  (the distributed-init barrier aligns the processes' run starts far
  tighter than wall clocks agree across hosts; the residual offset is
  visible in the ``skew`` counter track, which records the measured
  cross-host spread in-band);
* **restart attempts** (``run.a1.jsonl``, ... — obs.goodput run lineage)
  are auto-discovered like the ``.pN`` process siblings: each attempt
  renders its own lane group, offset on the time axis by its real
  distance from attempt 0's ``run_start``, with a ``restart gap`` slice
  spanning the crash→restart dead time the goodput report charges as
  badput;
* the **supervisor sibling** (``<stem>.sup.jsonl`` —
  parallel.supervisor's own scale-event ledger) renders as a
  ``supervisor`` marker lane: every ``scale`` record (shrink /
  re-expansion / preemption snapshot / drain) as an instant event on the
  job clock, so the elasticity timeline ``ledger_report`` prints is also
  visible in the merged trace.

Corrupt or truncated trailing lines — the signature of a crashed writer —
are skipped with a warning (``read_ledger(strict=False)``): crashed runs
are exactly the ones operators inspect. Pure stdlib + obs.ledger; no jax.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_dist.obs.ledger import read_ledger  # noqa: E402

# thread-row ids within each process lane (Chrome wants ints; names are
# attached via thread_name metadata events)
TID_STEPS, TID_COMM, TID_PHASES, TID_ALERTS = 0, 1, 2, 3
_TID_NAMES = {TID_STEPS: "steps", TID_COMM: "comm (overlaps device)",
              TID_PHASES: "phases", TID_ALERTS: "alerts"}
# per-request lanes (obs.reqtrace span events): each traced rid gets its
# own thread row from this base, so waterfalls render NEXT TO the step/
# phase lanes of the process that served them
TID_REQ_BASE = 16


def discover_ledgers(path: str) -> list:
    """``run.jsonl`` -> [run.jsonl, run.p1.jsonl, run.p2.jsonl, ...]."""
    root, ext = os.path.splitext(path)
    sibs = sorted(glob.glob(f"{glob.escape(root)}.p*{ext}"),
                  key=lambda p: _pidx_from_name(p, root, ext))
    return [path] + sibs


def _pidx_from_name(path: str, root: str, ext: str) -> int:
    tag = path[len(root) + 2: len(path) - len(ext)]
    return int(tag) if tag.isdigit() else 0


def _args(rec: dict, keys) -> dict:
    return {k: rec[k] for k in keys if rec.get(k) is not None}


def _process_events(records: list, pid: int) -> list:
    """One ledger's records -> Chrome trace events (ts/dur in µs, offset
    to the process's own run_start)."""
    starts = [r["ts"] for r in records if r.get("event") == "run_start"]
    t0 = starts[0] if starts else (records[0]["ts"] if records else 0.0)
    us = lambda ts: max((ts - t0) * 1e6, 0.0)
    ev: list = []
    name = None
    for r in records:
        if r.get("event") == "run_start":
            name = f"process {pid}" + (
                f" ({'/'.join(r['devices'])})" if r.get("devices") else "")
    ev.append({"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
               "args": {"name": name or f"process {pid}"}})
    for tid, tname in _TID_NAMES.items():
        ev.append({"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                   "args": {"name": tname}})
    # request lane assignment: one thread row per traced rid, in order of
    # first appearance (deterministic — the ledger's emit order is)
    req_tids: dict = {}

    def _req_tid(rid) -> int:
        tid = req_tids.get(rid)
        if tid is None:
            tid = TID_REQ_BASE + len(req_tids)
            req_tids[rid] = tid
            ev.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": f"request r{rid}"}})
        return tid

    for r in records:
        kind, ts = r.get("event"), r.get("ts", t0)
        if kind == "step":
            phases = [(p, r.get(f"{p}_s") or 0.0)
                      for p in ("data", "dispatch", "device")]
            end = us(ts)
            start = end - sum(d for _, d in phases) * 1e6
            meta = _args(r, ("step", "loss", "mfu", "throughput", "unit",
                             "steps_in_dispatch", "grad_norm",
                             "nonfinite_count", "warm"))
            cursor = start
            for pname, dur in phases:
                if dur <= 0:
                    continue
                ev.append({"ph": "X", "name": pname, "pid": pid,
                           "tid": TID_STEPS, "ts": cursor, "dur": dur * 1e6,
                           "args": meta})
                if pname == "device" and r.get("comm_s"):
                    # comm OVERLAPS the device block (obs.ledger schema
                    # note) — its own row, aligned under the device slice
                    ev.append({"ph": "X", "name": "comm", "pid": pid,
                               "tid": TID_COMM, "ts": cursor,
                               "dur": min(r["comm_s"], dur) * 1e6,
                               "args": {"comm_s": r["comm_s"]}})
                cursor += dur * 1e6
        elif kind == "epoch":
            start = r.get("start_ts")
            dur = r.get("seconds")
            if start is not None and dur:
                ev.append({"ph": "X", "name": f"epoch {r.get('epoch')}",
                           "pid": pid, "tid": TID_PHASES, "ts": us(start),
                           "dur": dur * 1e6,
                           "args": _args(r, ("loss", "throughput", "unit"))})
        elif kind == "decode":
            dur = r.get("seconds") or 0.0
            ev.append({"ph": "X", "name": "decode", "pid": pid,
                       "tid": TID_STEPS, "ts": us(ts) - dur * 1e6,
                       "dur": dur * 1e6,
                       "args": _args(r, ("tokens", "throughput", "cached"))})
        elif kind == "span":
            # span start/end are engine-clock; the wall emit ts anchors
            # the slice's END (spans close at emit — same convention as
            # the 'decode' slices above), so the lane lines up with the
            # step rows without cross-clock arithmetic
            dur = (r.get("end") or 0.0) - (r.get("start") or 0.0)
            ev.append({"ph": "X", "name": r.get("name") or "span",
                       "pid": pid, "tid": _req_tid(r.get("rid")),
                       "ts": us(ts) - dur * 1e6, "dur": dur * 1e6,
                       "args": _args(r, ("trace_id", "rid", "bucket",
                                         "tokens", "ticks", "reason",
                                         "pages_shared", "spec_drafted",
                                         "ttft_s", "tenant"))})
        elif kind in ("eval", "ckpt", "compile", "run_start", "run_end"):
            ev.append({"ph": "i", "name": kind, "pid": pid,
                       "tid": TID_PHASES, "ts": us(ts), "s": "t",
                       "args": _args(r, ("epoch", "loss", "ppl", "acc1",
                                         "path", "program", "status",
                                         "steps"))})
        elif kind == "stall":
            ev.append({"ph": "i", "name": "STALL", "pid": pid,
                       "tid": TID_ALERTS, "ts": us(ts), "s": "g",
                       "args": _args(r, ("idle_s", "threshold_s"))})
        elif kind == "health":
            ev.append({"ph": "i", "name": f"health:{r.get('kind')}",
                       "pid": pid, "tid": TID_ALERTS, "ts": us(ts),
                       "s": "g",
                       "args": _args(r, ("step", "policy", "action",
                                         "value", "loss"))})
        elif kind == "skew":
            ev.append({"ph": "C", "name": "skew spread (ms)", "pid": pid,
                       "ts": us(ts),
                       "args": {"spread": (r.get("spread_s") or 0) * 1e3}})
        elif kind == "hbm":
            ev.append({"ph": "C", "name": "hbm bytes", "pid": pid,
                       "ts": us(ts),
                       "args": {"in_use": r.get("bytes_in_use") or 0}})
    return ev


def merge_ledgers(paths: list) -> dict:
    """Paths -> the Chrome trace object ({"traceEvents": [...], ...})."""
    return merge_job([(0, paths)])


def _run_start_ts(records: list):
    for r in records:
        if r.get("event") == "run_start":
            return r["ts"]
    return records[0]["ts"] if records else None


def merge_job(groups: list, sup_records: list = ()) -> dict:
    """[(attempt_index, [lane paths]), ...] -> one Chrome trace. A single
    group is the classic multi-process merge; multiple groups (restart
    attempts, obs.goodput lineage) offset each attempt's lanes by its real
    wall distance from attempt 0's run_start and draw the restart gap.
    ``sup_records`` (the supervisor's ``<stem>.sup.jsonl`` sibling —
    elasticity decisions) render as their own marker lane: one instant
    event per ``scale`` record, on the job clock, so shrink/re-expansion
    and preemption-drain transitions sit visibly above the attempt lanes
    instead of silently missing from the merged trace."""
    events: list = []
    lanes = 0
    multi = len(groups) > 1
    job_t0 = None
    prev_end = None
    # read everything first: the per-attempt pid offset must clear the
    # HIGHEST process index seen anywhere (a 128-process job's attempt 0
    # must not share lane pids with attempt 1's low processes)
    loaded = []
    max_pid = 0
    for att, paths in groups:
        lane_records = []
        for i, p in enumerate(paths):
            try:
                records = read_ledger(p, strict=False)
            except OSError as e:
                print(f"warning: skipping {p}: {e}", file=sys.stderr)
                continue
            if not records:
                print(f"warning: {p}: no readable records", file=sys.stderr)
                continue
            pid = records[0].get("pid", i)
            max_pid = max(max_pid, pid)
            lane_records.append((pid, records))
        loaded.append((att, lane_records))
    pid_stride = max(100, max_pid + 1)
    for att, lane_records in loaded:
        pid_off = att * pid_stride if multi else 0
        att_events: list = []
        att_t0 = None
        att_end = None
        for pid, records in lane_records:
            att_events.extend(_process_events(records, pid))
            lanes += 1
            ts0 = _run_start_ts(records)
            if att_t0 is None:  # the group's first (p0) file anchors it
                att_t0 = ts0
            last = max(r.get("ts", 0.0) for r in records)
            att_end = last if att_end is None else max(att_end, last)
        if att_t0 is None:
            continue
        if job_t0 is None:
            job_t0 = att_t0
        offset_us = max((att_t0 - job_t0) * 1e6, 0.0)
        for e in att_events:
            e["pid"] += pid_off
            if "ts" in e:
                e["ts"] += offset_us
            if e.get("ph") == "M" and e.get("name") == "process_name" \
                    and multi:
                e["args"]["name"] = (f"attempt {att} · "
                                     f"{e['args'].get('name', '')}")
        events.extend(att_events)
        if multi and prev_end is not None and att_t0 > prev_end:
            gap = att_t0 - prev_end
            events.append({"ph": "X", "name": "restart gap",
                           "pid": pid_off, "tid": TID_PHASES,
                           "ts": offset_us - gap * 1e6, "dur": gap * 1e6,
                           "args": {"gap_s": round(gap, 3),
                                    "attempt": att}})
        prev_end = att_end
    scales = [r for r in (sup_records or ())
              if r.get("event") == "scale" and r.get("ts") is not None]
    # autoscaling markers (round 20, obs.autoscale): the capacity
    # monitor's scale_decision events and the supervisor's applied
    # follow-ups render as instants on the SAME supervisor lane, beside
    # the scale events they attribute — decision -> rescale -> new plan
    # hash reads left to right on one timeline
    decisions = [r for r in (sup_records or ())
                 if r.get("event") in ("scale_decision", "applied")
                 and r.get("ts") is not None]
    if (scales or decisions) and job_t0 is not None:
        # the supervisor lane: one stride past the HIGHEST attempt
        # ordinal (lane offsets key on the filename-stamped ordinal, not
        # list position — a lost intermediate attempt must not make this
        # lane collide with the last attempt's)
        sup_pid = pid_stride * (max((att for att, _ in groups),
                                    default=0) + 1)
        events.append({"ph": "M", "name": "process_name", "pid": sup_pid,
                       "tid": 0, "args": {"name": "supervisor"}})
        events.append({"ph": "M", "name": "thread_name", "pid": sup_pid,
                       "tid": 0, "args": {"name": "scale events"}})
        for r in scales:
            events.append({
                "ph": "i", "name": f"scale:{r.get('action')}",
                "pid": sup_pid, "tid": 0,
                "ts": max((r["ts"] - job_t0) * 1e6, 0.0), "s": "g",
                "args": _args(r, ("action", "processes", "epoch", "hosts",
                                  "step", "world_from", "shed",
                                  "decision"))})
        for r in decisions:
            name = (f"decision:{r.get('direction')}"
                    if r["event"] == "scale_decision"
                    else f"applied:{r.get('action')}")
            events.append({
                "ph": "i", "name": name, "pid": sup_pid, "tid": 0,
                "ts": max((r["ts"] - job_t0) * 1e6, 0.0), "s": "g",
                "args": _args(r, ("decision", "direction", "hosts_from",
                                  "target_hosts", "signal", "value",
                                  "threshold", "window_ticks", "bundle",
                                  "action", "processes", "epoch",
                                  "plan_hash"))})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"tool": "tpu_dist tools/trace_merge.py",
                          "processes": lanes,
                          "attempts": len(groups),
                          "scale_events": len(scales),
                          "autoscale_events": len(decisions),
                          "clock": ("per-process, zeroed at attempt 0's "
                                    "run_start" if multi else
                                    "per-process, zeroed at run_start")}}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="ledger JSONL path(s); the first path's .pN "
                         "siblings are auto-discovered")
    ap.add_argument("-o", "--out", default="",
                    help="output path (default: <first ledger>.trace.json)")
    ap.add_argument("--no-discover", action="store_true",
                    help="merge only the paths given (no .pN process or "
                    ".aN attempt glob)")
    args = ap.parse_args(argv)
    paths = list(args.paths)
    if args.no_discover:
        trace = merge_ledgers(paths)
    else:
        # restart lineage first (run.jsonl, run.a1.jsonl, ... — obs.
        # goodput), then each attempt's .pN process siblings
        from tpu_dist.obs.goodput import (attempt_ordinal,
                                          discover_attempt_paths)

        attempt_paths = discover_attempt_paths(paths[0]) or [paths[0]]
        groups = []
        for j, base in enumerate(attempt_paths):
            lane_paths = discover_ledgers(base)
            if j == 0:
                for extra in paths[1:]:
                    if extra not in lane_paths:
                        lane_paths.append(extra)
            # label by the filename's stamped ordinal, not list position:
            # a lost intermediate attempt must not renumber the rest
            groups.append((attempt_ordinal(base), lane_paths))
        # the supervisor's own scale-event sibling (parallel.supervisor
        # elasticity decisions) renders as a marker lane — without it a
        # merged trace of an elastic run silently omits every rescale
        from tpu_dist.obs.goodput import sup_sibling_path

        sup_path = sup_sibling_path(attempt_paths[0])
        sup_records = []
        if os.path.exists(sup_path):
            try:
                sup_records = read_ledger(sup_path, strict=False)
            except OSError as e:
                print(f"warning: skipping {sup_path}: {e}", file=sys.stderr)
        trace = merge_job(groups, sup_records=sup_records)
    if not trace["traceEvents"]:
        print("no records in any input ledger", file=sys.stderr)
        return 1
    out = args.out or (os.path.splitext(paths[0])[0] + ".trace.json")
    with open(out, "w") as f:
        json.dump(trace, f)
    n_att = trace["otherData"].get("attempts", 1)
    n_scale = trace["otherData"].get("scale_events", 0)
    print(f"{out}: {trace['otherData']['processes']} process lane(s)"
          + (f" across {n_att} attempts" if n_att > 1 else "")
          + (f", {n_scale} supervisor scale event(s)" if n_scale else "")
          + f", {len(trace['traceEvents'])} events — load in "
          "chrome://tracing or ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
