#!/bin/bash
# variant 2: launcher-driven multi-host (reference 2.run.sh:5 torch.distributed.launch).
# One process per host; HOSTS="host0 host1 ..." COORD=host0:8476 srun/ssh-style launch:
#   TPU_DIST_COORDINATOR=$COORD TPU_DIST_NUM_PROCESSES=$N TPU_DIST_PROCESS_ID=$i \
#     python scripts/2.distributed.py "$@"   # on each host i
# Single-host run:
python scripts/2.distributed.py "$@"
