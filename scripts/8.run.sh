#!/bin/bash
# variant 8: long-context LM. Examples:
#   bash scripts/8.run.sh                          # data parallel
#   bash scripts/8.run.sh --mesh data=2,seq=4      # ring-attention sequence parallel
#   bash scripts/8.run.sh --mesh data=4,model=2    # Megatron-style tensor parallel
python scripts/8.lm_longcontext.py "$@"
