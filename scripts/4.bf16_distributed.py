#!/usr/bin/env python
"""Variant 4 — mixed precision (apex AMP + apex DDP equivalent).

Reference: 4.apex_distributed2.py — `amp.initialize(model, optimizer)` +
`amp.scale_loss` dynamic loss scaling + apex DistributedDataParallel
(reference 4.apex_distributed2.py:177-178,289-290). The reference's CUDA-
stream prefetcher variant (4.apex_distributed.py:80-133) was disabled as
buggy upstream (4.apex_distributed2.py:80).

TPU-native: bf16 has fp32's exponent range, so mixed precision is a dtype
policy with NO loss scaling (--precision bf16; SURVEY.md §2b apex row).
Dynamic loss scaling is still available (--loss-scale 32768) for apex-semantics
parity experiments. The prefetcher role is filled by the double-buffered
device_put pipeline, enabled for every variant (tpu_dist/data/loader.py).
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tpu_dist.configs import TrainConfig, parse_config
from tpu_dist.engine import Trainer
from tpu_dist.parallel import launch

DEFAULTS = TrainConfig(arch="resnet18", epochs=10, batch_size=3200,
                       dataset="cifar10", variant="jit", precision="bf16")

if __name__ == "__main__":
    cfg = parse_config(defaults=DEFAULTS, description=__doc__)
    info = launch.initialize()
    print(f"[proc {info.process_id}/{info.num_processes}] precision={cfg.precision}")
    best = Trainer(cfg).fit()
    print(f"best_acc1 {best * 100:.3f}")
