#!/usr/bin/env python
"""Variant 3 — in-process spawn of the worker pool (mp.spawn equivalent).

Reference: 3.multiprocessing_distributed.py — `mp.spawn(main_worker,
nprocs=device_count)` forks one child per GPU, tcp://127.0.0.1:23456
rendezvous (reference 3.multiprocessing_distributed.py:84,102).

TPU-native: a single process already drives all local chips, so a local spawn
is unnecessary for TPU (SURVEY.md §2b process-manager row) — but the
capability is preserved for parity and for CPU-simulation of multi-host runs:
with --nprocs N this script forks N children, each claiming an equal slice of
CPU devices, rendezvousing over loopback TCP via jax.distributed (the tcp://
analog). With --nprocs 1 (TPU default) it trains directly.
"""

import os
import subprocess
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tpu_dist.configs import TrainConfig, parse_config
from tpu_dist.engine import Trainer
from tpu_dist.parallel import launch

DEFAULTS = TrainConfig(arch="resnet18", epochs=2, batch_size=3200,
                       dataset="cifar10", variant="jit")
RDZV = "127.0.0.1:23456"  # reference 3.multiprocessing_distributed.py:102


def spawn(nprocs: int, argv):
    """mp.spawn equivalent: fork workers with injected rendezvous env."""
    procs = []
    for rank in range(nprocs):
        env = dict(os.environ,
                   TPU_DIST_COORDINATOR=RDZV,
                   TPU_DIST_NUM_PROCESSES=str(nprocs),
                   TPU_DIST_PROCESS_ID=str(rank))
        procs.append(subprocess.Popen([sys.executable, __file__, *argv], env=env))
    rc = [p.wait() for p in procs]
    if any(rc):
        raise SystemExit(f"worker exit codes {rc}")


if __name__ == "__main__":
    nprocs = int(os.environ.pop("TPU_DIST_NPROCS_SPAWN", "0"))
    if nprocs > 1 and "TPU_DIST_PROCESS_ID" not in os.environ:
        spawn(nprocs, sys.argv[1:])
        sys.exit(0)
    cfg = parse_config(defaults=DEFAULTS, description=__doc__)
    info = launch.initialize()
    print(f"[proc {info.process_id}/{info.num_processes}] rendezvous={info.method}")
    best = Trainer(cfg).fit()
    print(f"best_acc1 {best * 100:.3f}")
