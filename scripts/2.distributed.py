#!/usr/bin/env python
"""Variant 2 — launcher-driven multi-process DDP (torch.distributed.launch equiv).

Reference: 2.distributed.py — `python -m torch.distributed.launch
--nproc_per_node=4` spawns one process per GPU; env:// rendezvous; per-process
batch division; DDP bucketed gradient allreduce (reference 2.distributed.py:
98,113,114; 2.run.sh:5).

TPU-native: one process per HOST (each process owns all its chips);
`jax.distributed.initialize` over DCN replaces env:// rendezvous
(TPU_DIST_COORDINATOR / TPU_DIST_NUM_PROCESSES / TPU_DIST_PROCESS_ID env, set
by scripts/2.run.sh); the gradient all-reduce is inserted by XLA exactly where
DDP's NCCL allreduce fired. Defaults mirror the reference: resnet18 / 2 epochs
(reference 2.distributed.py:30,39).
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tpu_dist.configs import TrainConfig, parse_config
from tpu_dist.engine import Trainer
from tpu_dist.parallel import launch

DEFAULTS = TrainConfig(arch="resnet18", epochs=2, batch_size=3200,
                       dataset="cifar10", variant="jit")

if __name__ == "__main__":
    cfg = parse_config(defaults=DEFAULTS, description=__doc__)
    info = launch.initialize()
    print(f"[proc {info.process_id}/{info.num_processes}] rendezvous={info.method}")
    best = Trainer(cfg).fit()
    print(f"best_acc1 {best * 100:.3f}")
