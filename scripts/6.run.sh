#!/bin/bash
# variant 6: Slurm multi-node (reference start.sh:5: srun -N2 --gres gpu:4)
# srun -N2 bash scripts/6.run.sh --data /path/to/imagenet
python scripts/6.distributed_slurm.py "$@"
