#!/usr/bin/env python
"""Variant 8 — long-context transformer LM over a dp x sp / tp / ep / pp mesh.

Beyond the reference (which is DP-only over image CNNs, SURVEY.md §2c):
trains a causal LM on a REAL token corpus through the shared LM engine
(tpu_dist.engine.lm_loop.LMTrainer) — epochs, distributed sampler rows,
K-steps-per-dispatch windows from HBM-resident rows, exact held-out
perplexity in every mode, mid-epoch resume — with the parallelism picked by
flags:

  --mesh data=8                 pure data parallel (jit)
  --mesh data=2,seq=4           sequence parallel: ring attention over 'seq'
  --mesh data=4,model=2         tensor parallel: Megatron shardings via GSPMD
  --mesh data=2,expert=4        MoE expert parallelism (with --num-experts)
  --mesh data=2,stage=4         pipeline parallel (--pp-schedule gpipe|1f1b)
  --mesh data=2,stage=2,model=2 pipeline x tensor parallel (Megatron inside
                                each stage via a GSPMD auto axis)

Data: --data points at a token file (.bin uint16 / .npy, nanoGPT-style);
absent, a deterministic synthetic affine corpus is generated so the loss
curve is meaningful without downloads. --steps N caps optimizer steps
(smoke runs); otherwise --epochs governs. Same multi-host launch story as
every other variant (tpu_dist.parallel.launch).
"""

import argparse
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


def parse_mesh(s):
    shape, axes = [], []
    for part in s.split(","):
        name, n = part.split("=")
        axes.append(name.strip())
        shape.append(int(n))
    return tuple(shape), tuple(axes)


def main():
    from tpu_dist.configs import LMConfig, add_args

    ap = argparse.ArgumentParser(description=__doc__)
    add_args(ap, LMConfig())
    ap.add_argument("--mesh", type=parse_mesh, default=None,
                    help="e.g. data=2,seq=4 | data=4,model=2 | data=8 "
                         "(overrides --mesh-shape/--mesh-axes)")
    ap.add_argument("--steps", type=int, default=0,
                    help="alias for --max-steps (cookbook compat)")
    ap.add_argument("--generate", type=int, default=0,
                    help="after training, greedy-decode N tokens from the "
                         "trained model and report how often they follow "
                         "the synthetic affine rule")
    args = ap.parse_args()

    from tpu_dist.parallel import launch
    info = launch.initialize()

    import dataclasses

    import jax
    import jax.numpy as jnp

    from tpu_dist.engine.lm_loop import LMTrainer

    cfg = LMConfig(**{f.name: getattr(args, f.name)
                      for f in dataclasses.fields(LMConfig)})
    if args.mesh:
        cfg = dataclasses.replace(cfg, mesh_shape=args.mesh[0],
                                  mesh_axes=args.mesh[1])
    if args.steps:
        cfg = dataclasses.replace(cfg, max_steps=args.steps)

    trainer = LMTrainer(cfg)
    if jax.process_index() == 0:
        print(f"[proc {info.process_id}/{info.num_processes}] "
              f"mesh={dict(trainer.mesh.shape)} mode={trainer.mode} "
              f"corpus={trainer.train_ds.name} rows={len(trainer.train_ds)} "
              f"tokens/step={cfg.batch_size * cfg.seq_len}")
    if cfg.max_restarts > 0:
        # in-process self-healing (parallel.supervisor): halts/crashes
        # rebuild the trainer with attempt lineage + newest-valid resume.
        # The prebuilt trainer serves attempt 0 (avoids a second compile);
        # restarts rebuild, and the --generate path below must decode the
        # LAST attempt's state, so the factory tracks it. Process-killing
        # faults need the subprocess flavor:
        # python -m tpu_dist.supervise -- python scripts/8...
        from tpu_dist.parallel.supervisor import run_supervised
        current = {"trainer": trainer, "used": False}

        def build(run_cfg):
            if current["used"]:
                # drop the dead attempt's trainer BEFORE constructing the
                # replacement: its params/opt-state must be collectable
                # while the rebuild re-allocates them (HBM headroom)
                current["trainer"] = None
                current["trainer"] = LMTrainer(run_cfg)
            current["used"] = True  # one-shot: attempt 0 and ONLY attempt
            # 0 gets the prebuilt trainer, even when it died pre-step
            return current["trainer"]

        best_ppl = run_supervised(build, cfg)
        trainer = current["trainer"]
    else:
        best_ppl = trainer.fit()
    if jax.process_index() == 0 and not cfg.evaluate:
        print(f"throughput {trainer.last_tok_s:,.0f} tokens/sec "
              f"({trainer.mode}) best_ppl {best_ppl:.2f}")

    if args.generate:
        # decode on host-replicated params; the gather is a COLLECTIVE for
        # cross-host sharded modes, so EVERY process enters it — only the
        # decode itself is process-0-only. pp's stacked layout is restored
        # to the dense tree first.
        from tpu_dist.engine.checkpoint import gather_to_host
        from tpu_dist.engine.generate import generate
        host_params = gather_to_host(trainer.state.params)
    if args.generate and jax.process_index() == 0:
        if trainer.use_pp:
            from tpu_dist.parallel.pp import unstack_pipeline_params
            host_params = unstack_pipeline_params(host_params)
        n = min(args.generate, cfg.seq_len - 2)
        seed = 3
        prompt = jnp.asarray([[seed, (seed * 5 + 7) % trainer.vocab_size]],
                             jnp.int32)
        # sp's model closes over mesh axis names (ring attention); decode
        # with the full-attention equivalent — same weights, same math.
        # trainer._sp_ctor already encodes the dense-vs-MoE class choice
        # with the right ctor kwargs (one definition, lm_loop._build_steps);
        # tiny_lm's **_ catch-all would otherwise silently swallow MoE
        # kwargs and build a model that cannot apply the trained params.
        # Dense AND MoE models decode through the KV cache (round-5:
        # models.transformer.attend_maybe_cached is shared).
        gen_model = trainer._sp_ctor() if trainer.use_sp else trainer.model
        out = np.asarray(generate(gen_model, host_params, prompt, steps=n,
                                  use_cache=True))
        follows = sum(int(out[0, i + 1])
                      == (int(out[0, i]) * 5 + 7) % trainer.vocab_size
                      for i in range(1, n + 1))
        print(f"generated {n} tokens, {follows}/{n} follow the affine rule: "
              f"{out[0].tolist()}")


if __name__ == "__main__":
    main()
