#!/usr/bin/env python
"""Variant 8 — long-context transformer LM over a dp x sp / dp x tp mesh.

Beyond the reference (which is DP-only over image CNNs, SURVEY.md §2c):
trains a causal LM with the parallelism picked by flags:

  --mesh data=8                 pure data parallel (jit)
  --mesh data=2,seq=4           sequence parallel: ring attention over 'seq'
  --mesh data=4,model=2         tensor parallel: Megatron shardings via GSPMD
  --mesh data=2,stage=4         pipeline parallel: GPipe microbatches over
                                'stage' (--pp-microbatches)

Data is a synthetic deterministic token stream (affine next-token rule +
noise) so the loss curve is meaningful without downloads. Prints per-step
loss and tokens/sec; same multi-host launch story as every other variant
(tpu_dist.parallel.launch).
"""

import argparse
import sys
import time
from functools import partial

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


def parse_mesh(s):
    shape, axes = [], []
    for part in s.split(","):
        name, n = part.split("=")
        axes.append(name.strip())
        shape.append(int(n))
    return tuple(shape), tuple(axes)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mesh", type=parse_mesh, default=None,
                    help="e.g. data=2,seq=4 | data=4,model=2 | data=8")
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=16, help="global batch (sequences)")
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--vocab-size", type=int, default=512)
    ap.add_argument("--num-layers", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--num-heads", type=int, default=8)
    ap.add_argument("--lr", type=float, default=3e-2)
    ap.add_argument("--precision", default="fp32", choices=["fp32", "bf16"])
    ap.add_argument("--print-freq", type=int, default=10)
    ap.add_argument("--fsdp", action="store_true",
                    help="shard params+optimizer state over the data axis "
                         "(ZeRO-3 placement; same step function)")
    ap.add_argument("--num-experts", type=int, default=0,
                    help="MoE feed-forward with N experts (0 = dense); with "
                         "--mesh data=2,expert=4 experts shard over the "
                         "'expert' axis (GShard-style expert parallelism)")
    ap.add_argument("--pp-microbatches", type=int, default=4,
                    help="GPipe microbatches per step (with a 'stage' axis)")
    ap.add_argument("--router-top-k", type=int, default=1, choices=[1, 2],
                    help="MoE routing: 1 = Switch top-1, 2 = GShard top-2")
    ap.add_argument("--attn", default="full",
                    choices=["full", "blockwise", "flash"],
                    help="attention flavor: full O(L^2) memory; blockwise "
                         "online-softmax O(L*block); flash = Pallas forward "
                         "kernel + recompute backward (non-sp meshes)")
    ap.add_argument("--attn-block", type=int, default=512,
                    help="KV block size for blockwise/flash recompute")
    ap.add_argument("--remat", action="store_true",
                    help="jax.checkpoint each transformer block (trade "
                         "FLOPs for HBM; the long-context memory lever)")
    ap.add_argument("--checkpoint-dir", default="",
                    help="save checkpoints here (also on Ctrl-C); empty = off")
    ap.add_argument("--save-freq", type=int, default=0,
                    help="checkpoint every N steps (0 = only at end/interrupt)")
    ap.add_argument("--resume", default="",
                    help="checkpoint to resume from (continues at its step)")
    ap.add_argument("--eval-size", type=int, default=0,
                    help="hold out N sequences (same distribution, fresh "
                         "seed) and report val loss/perplexity at every "
                         "print and at the end (dense-mesh modes)")
    ap.add_argument("--generate", type=int, default=0,
                    help="after training, greedy-decode N tokens from the "
                         "trained model and report how often they follow "
                         "the synthetic affine rule")
    args = ap.parse_args()

    from tpu_dist.parallel import launch
    info = launch.initialize()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_dist.engine import checkpoint as ckpt
    from tpu_dist.engine.lm_steps import (make_lm_batches,
                                          make_lm_sp_train_step,
                                          make_lm_train_step)
    from tpu_dist.engine.state import TrainState
    from tpu_dist.models.transformer import tiny_lm
    from tpu_dist.ops import make_optimizer, make_policy
    from tpu_dist.parallel.mesh import make_mesh, replicated
    from tpu_dist.parallel.tp import shard_lm_params

    mesh_shape, mesh_axes = args.mesh if args.mesh else ((jax.device_count(),),
                                                        ("data",))
    mesh = make_mesh(mesh_shape, mesh_axes)
    policy = make_policy(args.precision)
    if args.attn != "full":
        from tpu_dist.ops.flash_attention import (blockwise_attention_fn,
                                                  flash_attention_fn)
        attn_fn = (blockwise_attention_fn(args.attn_block)
                   if args.attn == "blockwise"
                   else flash_attention_fn(recompute_block=args.attn_block))
    else:
        from tpu_dist.models.transformer import full_attention
        attn_fn = full_attention
    lm_kw = dict(vocab_size=args.vocab_size, num_layers=args.num_layers,
                 d_model=args.d_model, num_heads=args.num_heads,
                 max_len=args.seq_len, dtype=policy.compute_dtype,
                 attn_fn=attn_fn, remat=args.remat)
    if args.num_experts:
        if args.remat:
            raise SystemExit("--remat supports the dense TransformerLM only")
        from tpu_dist.models.moe import MoETransformerLM
        moe_kw = {k: v for k, v in lm_kw.items() if k != "remat"}
        model = MoETransformerLM(num_experts=args.num_experts,
                                 router_top_k=args.router_top_k, **moe_kw)
    else:
        model = tiny_lm(**lm_kw)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, args.seq_len), jnp.int32),
                        train=False)["params"]
    tx = make_optimizer(args.lr, 0.9, 0.0, steps_per_epoch=10 ** 6)
    state = TrainState.create(params, {}, tx)

    use_sp = "seq" in mesh.axis_names and mesh.shape["seq"] > 1
    use_tp = "model" in mesh.axis_names and mesh.shape["model"] > 1
    use_ep = "expert" in mesh.axis_names and mesh.shape["expert"] > 1
    use_pp = "stage" in mesh.axis_names and mesh.shape["stage"] > 1
    if use_pp and (use_sp or use_tp or use_ep or args.num_experts or args.fsdp):
        raise SystemExit("a 'stage' mesh axis composes only with 'data' "
                         "(GPipe over dense TransformerLM blocks)")
    if args.fsdp and (use_sp or use_tp or use_ep):
        print("warning: --fsdp applies to the pure data-parallel layout; "
              "ignored with a seq/model/expert mesh axis", flush=True)
    if use_ep and not args.num_experts:
        raise SystemExit("an 'expert' mesh axis requires --num-experts > 0")
    if use_sp and args.num_experts:
        raise SystemExit("MoE + sequence parallelism not supported yet "
                         "(ring attention path builds the dense model)")
    if use_sp and args.attn != "full":
        print("warning: a 'seq' mesh axis uses ring attention; "
              f"--attn {args.attn} ignored", flush=True)
    if use_tp and args.num_experts:
        raise SystemExit("MoE + tensor parallelism not supported: the TP "
                         "rules don't shard 3-D expert weights — use "
                         "--mesh data=N,expert=M instead")
    if use_pp:
        # stacked layout BEFORE TrainState.create so the optimizer state
        # mirrors it (also makes it the checkpoint/resume template)
        from tpu_dist.parallel.pp import (make_lm_pp_train_step,
                                          shard_state_pp,
                                          stack_pipeline_params)
        params = stack_pipeline_params(params, mesh.shape["stage"])
        state = TrainState.create(params, {}, tx)

    def place(st):
        """Apply the mode's sharding; also re-places a resumed host state."""
        if use_pp:
            return shard_state_pp(mesh, st)
        if use_sp:
            return jax.device_put(st, replicated(mesh))
        if use_ep:
            from tpu_dist.parallel.ep import shard_state_ep
            return shard_state_ep(mesh, st)
        if use_tp:
            return TrainState(
                step=jax.device_put(st.step, NamedSharding(mesh, P())),
                params=shard_lm_params(mesh, st.params), batch_stats={},
                opt_state=jax.device_put(st.opt_state,
                                         NamedSharding(mesh, P())),
                loss_scale=None)
        if args.fsdp:
            from tpu_dist.parallel.fsdp import shard_state_fsdp
            return shard_state_fsdp(mesh, st)
        return jax.device_put(st, replicated(mesh))

    if use_pp:
        step = make_lm_pp_train_step(model, tx, mesh, args.pp_microbatches)
        data_spec = P("data", None)
    elif use_sp:
        step = make_lm_sp_train_step(partial(tiny_lm, **lm_kw), tx, mesh)
        data_spec = P("data", "seq")
    else:
        step = make_lm_train_step(model, tx, mesh)
        data_spec = P("data")

    # model geometry stamped into every checkpoint; a mismatched resume must
    # fail with a clear message, not a deep XLA shape error (or worse: a
    # pp checkpoint resumed with a different stage count reshards the
    # stage-stacked blocks wrongly and silently drops layers)
    geometry = {"vocab_size": args.vocab_size, "num_layers": args.num_layers,
                "d_model": args.d_model, "num_heads": args.num_heads,
                "seq_len": args.seq_len, "num_experts": args.num_experts,
                "pp_stages": mesh.shape["stage"] if use_pp else 0}

    start_step = 0
    if args.resume:
        # validate geometry from the meta header BEFORE deserializing: a
        # wrong-shaped blob fails opaquely (or, for pp stage counts, loads
        # and silently missplits the stage-stacked blocks)
        meta = ckpt.read_checkpoint_meta(args.resume)
        bad = {k: (meta[k], v) for k, v in geometry.items()
               if k in meta and meta[k] != v}
        if bad:
            raise SystemExit(
                "--resume checkpoint has different model geometry: " +
                ", ".join(f"{k}: checkpoint {a} vs flags {b}"
                          for k, (a, b) in bad.items()))
        # load into the freshly-initialized (host) template, THEN shard —
        # works for every mode because placement is orthogonal to the blob
        state, meta = ckpt.load_checkpoint(args.resume, state)
        start_step = int(np.asarray(state.step))
        if jax.process_index() == 0:
            print(f"=> resumed from {args.resume} (step {start_step})",
                  flush=True)
    state = place(state)

    # synthetic affine-rule token stream (learnable, deterministic)
    def affine_stream(n_rows, seed):
        rng = np.random.default_rng(seed)
        start = rng.integers(0, args.vocab_size, (n_rows, 1))
        rows = [start]
        for _ in range(args.seq_len):
            nxt = (rows[-1] * 5 + 7) % args.vocab_size
            flip = rng.random(nxt.shape) < 0.05
            rows.append(np.where(flip,
                                 rng.integers(0, args.vocab_size, nxt.shape),
                                 nxt))
        return np.concatenate(rows, axis=1).astype(np.int32)

    inputs, targets = make_lm_batches(affine_stream(args.batch_size, seed=0))
    sh = NamedSharding(mesh, data_spec)
    inputs = jax.device_put(inputs, sh)
    targets = jax.device_put(targets, sh)

    eval_step = None
    if args.eval_size:
        if use_sp or use_pp:
            raise SystemExit("--eval-size supports the dense-mesh modes "
                             "(dp/fsdp/tp/ep); sp/pp evaluate via their "
                             "train-loss curves")
        if args.eval_size % mesh.shape["data"]:
            raise SystemExit(f"--eval-size {args.eval_size} must divide by "
                             f"the data axis ({mesh.shape['data']})")
        from tpu_dist.engine.lm_steps import make_lm_eval_step
        eval_step = make_lm_eval_step(model, mesh)
        vi, vt = make_lm_batches(affine_stream(args.eval_size, seed=1))
        vi = jax.device_put(vi, sh)
        vt = jax.device_put(vt, sh)

        eval_secs = [0.0]  # excluded from the throughput window

        def evaluate(st):
            t = time.perf_counter()
            m = jax.device_get(eval_step(st.params, vi, vt))
            eval_secs[0] += time.perf_counter() - t
            loss = float(m["loss_sum"]) / float(m["count"])
            return loss, float(np.exp(min(loss, 30.0))), \
                float(m["correct1"]) / float(m["count"])

    mode = ("pp-gpipe" if use_pp else
            "sp-ring" if use_sp else
            "ep-moe" if use_ep else
            "tp" if use_tp else
            "fsdp" if args.fsdp else
            ("dp-moe" if args.num_experts else "dp"))
    if jax.process_index() == 0:
        print(f"[proc {info.process_id}/{info.num_processes}] mesh={dict(mesh.shape)} "
              f"mode={mode} tokens/step={args.batch_size * args.seq_len}")
    last_saved = [-1]

    def save(st, step_no):
        if not args.checkpoint_dir or step_no == last_saved[0]:
            return  # off, or this exact step already on disk
        # gathers cross-host shards inside (collective) — every process calls
        ckpt.save_checkpoint(args.checkpoint_dir, st, 0, 0.0, "lm",
                             is_best=False,
                             extra_meta={"mode": mode, **geometry})
        last_saved[0] = step_no

    key = jax.random.PRNGKey(1)
    i = start_step
    t0 = time.perf_counter()
    timed_from = start_step  # first step compiles; throughput excludes it
    try:
        for i in range(start_step, args.steps):
            state, metrics = step(state, inputs, targets, key)
            if i == start_step and args.steps - start_step > 1:
                jax.block_until_ready(metrics)
                t0 = time.perf_counter()
                timed_from = start_step + 1
            if i % args.print_freq == 0 or i == args.steps - 1:
                m = jax.device_get(metrics)
                loss = float(m["loss_sum"]) / float(m["count"])
                acc = float(m["correct1"]) / float(m["count"])
                if eval_step is not None:
                    vl, ppl, va = evaluate(state)
                    if jax.process_index() == 0:
                        print(f"step {i:4d} loss {loss:.4f} acc {acc:.3f} | "
                              f"val_loss {vl:.4f} ppl {ppl:.2f} "
                              f"val_acc {va:.3f}")
                elif jax.process_index() == 0:
                    print(f"step {i:4d} loss {loss:.4f} acc {acc:.3f}")
            if args.save_freq and (i + 1) % args.save_freq == 0:
                save(state, i + 1)
    except KeyboardInterrupt:
        # best-effort on multi-host sharded state: peers interrupted at a
        # different step would desync the collective gather — single-host
        # (the normal Ctrl-C case) is always safe
        save(state, i + 1)
        if jax.process_index() == 0:
            print(("interrupted — checkpoint saved at step "
                   f"{int(np.asarray(jax.device_get(state.step)))}; "
                   "resume with --resume") if args.checkpoint_dir else
                  "interrupted — no --checkpoint-dir, nothing saved",
                  flush=True)
        raise
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0
    if eval_step is not None:
        dt -= eval_secs[0]  # eval (incl. its compile) is not training time
    save(state, args.steps)
    toks = (args.steps - timed_from) * args.batch_size * args.seq_len
    if jax.process_index() == 0:
        print(f"throughput {toks / dt:,.0f} tokens/sec ({mode}, "
              f"{args.steps - timed_from} timed steps)")

    if args.generate:
        # decode on host-replicated params; the gather is a COLLECTIVE for
        # cross-host sharded modes, so EVERY process enters it — only the
        # decode itself is process-0-only. pp's stacked layout is restored
        # to the dense tree first.
        from tpu_dist.engine.checkpoint import gather_to_host
        from tpu_dist.engine.generate import generate
        host_params = gather_to_host(state.params)
    if args.generate and jax.process_index() == 0:
        if use_pp:
            from tpu_dist.parallel.pp import unstack_pipeline_params
            host_params = unstack_pipeline_params(host_params)
        n = min(args.generate, args.seq_len - 2)
        seed = 3
        prompt = jnp.asarray([[seed, (seed * 5 + 7) % args.vocab_size]],
                             jnp.int32)
        # sp's model closes over mesh axis names (ring attention); decode
        # with the dense equivalent — same weights, same math. Dense models
        # decode through the KV cache; MoE uses full recompute.
        gen_model = tiny_lm(**lm_kw) if use_sp else model
        out = np.asarray(generate(gen_model, host_params, prompt, steps=n,
                                  use_cache=not args.num_experts))
        follows = sum(int(out[0, i + 1])
                      == (int(out[0, i]) * 5 + 7) % args.vocab_size
                      for i in range(1, n + 1))
        print(f"generated {n} tokens, {follows}/{n} follow the affine rule: "
              f"{out[0].tolist()}")


if __name__ == "__main__":
    main()
