#!/usr/bin/env python
"""Variant 8 — long-context transformer LM over a dp x sp / tp / ep / pp mesh.

Beyond the reference (which is DP-only over image CNNs, SURVEY.md §2c):
trains a causal LM on a REAL token corpus through the shared LM engine
(tpu_dist.engine.lm_loop.LMTrainer) — epochs, distributed sampler rows,
K-steps-per-dispatch windows from HBM-resident rows, exact held-out
perplexity in every mode, mid-epoch resume — with the parallelism picked by
flags:

  --mesh data=8                 pure data parallel (jit)
  --mesh data=2,seq=4           sequence parallel: ring attention over 'seq'
  --mesh data=4,model=2         tensor parallel: Megatron shardings via GSPMD
  --mesh data=2,expert=4        MoE expert parallelism (with --num-experts)
  --mesh data=2,stage=4         pipeline parallel (--pp-schedule gpipe|1f1b)
  --mesh data=2,stage=2,model=2 pipeline x tensor parallel (Megatron inside
                                each stage via a GSPMD auto axis)

Data: --data points at a token file (.bin uint16 / .npy, nanoGPT-style);
absent, a deterministic synthetic affine corpus is generated so the loss
curve is meaningful without downloads. --steps N caps optimizer steps
(smoke runs); otherwise --epochs governs. Same multi-host launch story as
every other variant (tpu_dist.parallel.launch).
"""

import argparse
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


def parse_mesh(s):
    shape, axes = [], []
    for part in s.split(","):
        name, n = part.split("=")
        axes.append(name.strip())
        shape.append(int(n))
    return tuple(shape), tuple(axes)


def main():
    from tpu_dist.configs import LMConfig, add_args

    ap = argparse.ArgumentParser(description=__doc__)
    add_args(ap, LMConfig())
    ap.add_argument("--mesh", type=parse_mesh, default=None,
                    help="e.g. data=2,seq=4 | data=4,model=2 | data=8 "
                         "(overrides --mesh-shape/--mesh-axes)")
    ap.add_argument("--steps", type=int, default=0,
                    help="alias for --max-steps (cookbook compat)")
    ap.add_argument("--generate", type=int, default=0,
                    help="after training, greedy-decode N tokens from the "
                         "trained model and report how often they follow "
                         "the synthetic affine rule")
    ap.add_argument("--serve", type=int, default=0,
                    help="after training, stand up the long-context "
                         "ServeEngine (chunked prefill + sp-sharded paged "
                         "KV pool) on the SAME devices the sequence axis "
                         "trained on and serve N short requests plus one "
                         "long prompt: chunked when training ran without "
                         "sp, sequence-parallel prefill into the sharded "
                         "pool when it did")
    args = ap.parse_args()

    from tpu_dist.parallel import launch
    info = launch.initialize()

    import dataclasses

    import jax
    import jax.numpy as jnp

    from tpu_dist.engine.lm_loop import LMTrainer

    cfg = LMConfig(**{f.name: getattr(args, f.name)
                      for f in dataclasses.fields(LMConfig)})
    if args.mesh:
        cfg = dataclasses.replace(cfg, mesh_shape=args.mesh[0],
                                  mesh_axes=args.mesh[1])
    if args.steps:
        cfg = dataclasses.replace(cfg, max_steps=args.steps)

    trainer = LMTrainer(cfg)
    if jax.process_index() == 0:
        print(f"[proc {info.process_id}/{info.num_processes}] "
              f"mesh={dict(trainer.mesh.shape)} mode={trainer.mode} "
              f"corpus={trainer.train_ds.name} rows={len(trainer.train_ds)} "
              f"tokens/step={cfg.batch_size * cfg.seq_len}")
    if cfg.max_restarts > 0:
        # in-process self-healing (parallel.supervisor): halts/crashes
        # rebuild the trainer with attempt lineage + newest-valid resume.
        # The prebuilt trainer serves attempt 0 (avoids a second compile);
        # restarts rebuild, and the --generate path below must decode the
        # LAST attempt's state, so the factory tracks it. Process-killing
        # faults need the subprocess flavor:
        # python -m tpu_dist.supervise -- python scripts/8...
        from tpu_dist.parallel.supervisor import run_supervised
        current = {"trainer": trainer, "used": False}

        def build(run_cfg):
            if current["used"]:
                # drop the dead attempt's trainer BEFORE constructing the
                # replacement: its params/opt-state must be collectable
                # while the rebuild re-allocates them (HBM headroom)
                current["trainer"] = None
                current["trainer"] = LMTrainer(run_cfg)
            current["used"] = True  # one-shot: attempt 0 and ONLY attempt
            # 0 gets the prebuilt trainer, even when it died pre-step
            return current["trainer"]

        best_ppl = run_supervised(build, cfg)
        trainer = current["trainer"]
    else:
        best_ppl = trainer.fit()
    if jax.process_index() == 0 and not cfg.evaluate:
        print(f"throughput {trainer.last_tok_s:,.0f} tokens/sec "
              f"({trainer.mode}) best_ppl {best_ppl:.2f}")

    if args.generate or args.serve:
        # decode on host-replicated params; the gather is a COLLECTIVE for
        # cross-host sharded modes, so EVERY process enters it — only the
        # decode itself is process-0-only. pp's stacked layout is restored
        # to the dense tree first.
        from tpu_dist.engine.checkpoint import gather_to_host
        from tpu_dist.engine.generate import generate
        host_params = gather_to_host(trainer.state.params)
    if args.generate and jax.process_index() == 0:
        if trainer.use_pp:
            from tpu_dist.parallel.pp import unstack_pipeline_params
            host_params = unstack_pipeline_params(host_params)
        n = min(args.generate, cfg.seq_len - 2)
        seed = 3
        prompt = jnp.asarray([[seed, (seed * 5 + 7) % trainer.vocab_size]],
                             jnp.int32)
        # sp's model closes over mesh axis names (ring attention); decode
        # with the full-attention equivalent — same weights, same math.
        # trainer._sp_ctor already encodes the dense-vs-MoE class choice
        # with the right ctor kwargs (one definition, lm_loop._build_steps);
        # tiny_lm's **_ catch-all would otherwise silently swallow MoE
        # kwargs and build a model that cannot apply the trained params.
        # Dense AND MoE models decode through the KV cache (round-5:
        # models.transformer.attend_maybe_cached is shared).
        gen_model = trainer._sp_ctor() if trainer.use_sp else trainer.model
        out = np.asarray(generate(gen_model, host_params, prompt, steps=n,
                                  use_cache=True))
        follows = sum(int(out[0, i + 1])
                      == (int(out[0, i]) * 5 + 7) % trainer.vocab_size
                      for i in range(1, n + 1))
        print(f"generated {n} tokens, {follows}/{n} follow the affine rule: "
              f"{out[0].tolist()}")

    if args.serve and jax.process_index() == 0:
        # the serving half of the long-context story: the engine stands up
        # on the SAME local devices the model's sequence axis trained on.
        # With --mesh ...,seq=N the KV pool shards its page arenas over an
        # ('sp',) submesh of those N devices and long prompts prefill
        # sequence-parallel (ring attention, scattered KV writes); without
        # sp the long prompt goes through chunked prefill instead — either
        # way short requests keep decoding in between.
        from tpu_dist.engine.serve import (DecodeRequest, ServeConfig,
                                           ServeEngine)
        from tpu_dist.parallel.mesh import SEQ_AXIS, SP_AXIS, make_mesh

        if trainer.use_pp:
            print("--serve: pipeline-stacked params don't decode through "
                  "the serving engine (use --generate's dense restore)")
            return
        sp_n = (int(trainer.mesh.shape[SEQ_AXIS])
                if trainer.use_sp else 1)
        sp_n = min(sp_n, len(jax.local_devices()))
        page_size = 8
        if sp_n > 1 and cfg.seq_len < 2 * sp_n * page_size:
            print(f"--serve: seq_len {cfg.seq_len} too short for a "
                  f"{sp_n}-device sp pool; serving chunked on one device")
            sp_n = 1
        step = sp_n * page_size
        serve_len = (cfg.seq_len // step) * step
        serve_model = (trainer._sp_ctor() if trainer.use_sp
                       else trainer.model)
        mesh = (make_mesh((sp_n,), (SP_AXIS,),
                          devices=jax.local_devices()[:sp_n])
                if sp_n > 1 else None)
        thresh = serve_len // 2
        scfg = ServeConfig(
            max_slots=4, page_size=page_size,
            num_pages=4 * (serve_len // page_size), max_len=serve_len,
            prefill_chunk=2 * page_size,
            sp_prefill_threshold=thresh if mesh is not None else 0)
        eng = ServeEngine(serve_model, host_params, scfg, mesh=mesh)

        def affine(seed, n):
            toks = [seed % trainer.vocab_size]
            for _ in range(n - 1):
                toks.append((toks[-1] * 5 + 7) % trainer.vocab_size)
            return np.asarray(toks, np.int32)

        long_len = thresh if mesh is not None else serve_len // 2
        reqs = [DecodeRequest(0, affine(3, long_len), 8)]
        reqs += [DecodeRequest(i + 1, affine(3 + i, 6), 8)
                 for i in range(args.serve)]
        comps = eng.run(reqs)
        follows = total = 0
        for c in comps:
            toks = [int(t) for t in c.tokens]
            gen0 = c.prompt_len  # first generated index
            follows += sum(toks[i + 1] == (toks[i] * 5 + 7)
                           % trainer.vocab_size
                           for i in range(gen0 - 1, len(toks) - 1))
            total += len(toks) - gen0
        st = eng.stats()
        print(f"served {len(comps)}/{len(reqs)} requests "
              f"(1 long {long_len}-token prompt + {args.serve} short) on "
              f"{sp_n} device(s): {st['sp_prefills']} sp prefills, "
              f"{st['chunk_ticks']} chunk ticks, occupancy "
              f"{st['occupancy'] * 100:.0f}%, {follows}/{total} generated "
              "tokens follow the affine rule")


if __name__ == "__main__":
    main()
