#!/bin/bash
# variant 5.2: MNIST CNN (reference 5.2.run.mnist.sh:3); fp16-allreduce-equiv off
python scripts/5.2.mnist.py --grad-compression none "$@"
