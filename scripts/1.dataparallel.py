#!/usr/bin/env python
"""Variant 1 — single-process multi-device (nn.DataParallel equivalent).

Reference: 1.dataparallel.py — one process drives 4 GPUs via scatter/gather
(reference 1.dataparallel.py:109), global batch NOT pre-divided
(reference 1.dataparallel.py:140-144), defaults resnet101 / 5 epochs / batch
3200 / CIFAR10 (reference 1.dataparallel.py:33,42,44).

TPU-native: one process already addresses every local chip; `jit` over a 1-D
data mesh IS DataParallel without the scatter/gather host bottleneck (SURVEY.md
§7 'DataParallel analog'). No launcher, no rendezvous.
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tpu_dist.configs import TrainConfig, parse_config
from tpu_dist.engine import Trainer

DEFAULTS = TrainConfig(arch="resnet101", epochs=5, batch_size=3200,
                       dataset="cifar10", variant="jit",
                       log_csv="dataparallel.csv")

if __name__ == "__main__":
    cfg = parse_config(defaults=DEFAULTS, description=__doc__)
    best = Trainer(cfg).fit()
    print(f"best_acc1 {best * 100:.3f}")
