#!/usr/bin/env python
"""Variant 5 — explicit ring-allreduce data parallelism (Horovod equivalent).

Reference: 5.horovod_distributed.py — hvd.init + broadcast_parameters +
DistributedOptimizer with fp16-compressed gradient allreduce (reference
5.horovod_distributed.py:92,116,123-125).

TPU-native: the shard_map engine — one program per device with EXPLICIT
`psum` gradient reduction (XLA picks ring/tree on ICI automatically,
SURVEY.md §2c). --grad-compression bf16 mirrors hvd.Compression.fp16;
--gradient-predivide-factor mirrors horovod's predivide placement. Parameter
broadcast-from-rank-0 is replaced by replicated initialization from one seed
(numerically identical start, no broadcast needed).
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tpu_dist.configs import TrainConfig, parse_config
from tpu_dist.engine import Trainer
from tpu_dist.parallel import launch

DEFAULTS = TrainConfig(arch="resnet18", epochs=10, batch_size=3200,
                       dataset="cifar10", variant="shard_map",
                       grad_compression="bf16")

if __name__ == "__main__":
    cfg = parse_config(defaults=DEFAULTS, description=__doc__)
    info = launch.initialize()
    print(f"[proc {info.process_id}/{info.num_processes}] "
          f"compression={cfg.grad_compression}")
    best = Trainer(cfg).fit()
    print(f"best_acc1 {best * 100:.3f}")
