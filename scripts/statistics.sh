#!/bin/bash
# TPU telemetry sampler (reference statistics.sh:1-4 nvidia-smi 500ms CSV).
# No nvidia-smi on TPU; device utilization/memory come from the JAX profiler
# (--profile-dir) — this script samples host-side RSS + the libtpu runtime
# metrics endpoint if present.
OUT=${1:-tpu_log.csv}
echo "ts,host_rss_kb" > "$OUT"
while true; do
  echo "$(date +%s.%N),$(grep VmRSS /proc/self/status | awk '{print $2}')" >> "$OUT"
  sleep 0.5
done
