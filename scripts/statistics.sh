#!/bin/bash
# TPU telemetry sampler (reference statistics.sh:1-4 nvidia-smi 500ms CSV).
# No nvidia-smi on TPU; device HBM is only visible to the owning XLA client,
# so the in-process sampler (--telemetry-csv, tpu_dist/utils/telemetry.py)
# records device bytes-in-use/peak/limit at the 500 ms cadence; this script
# is the out-of-process companion, sampling the TRAINING process's host RSS
# at the same cadence. Deeper device views: --profile-dir (XLA trace) and
# the peak-HBM column in the per-epoch CSV. Usage: statistics.sh <pid> [out.csv]; with no pid it
# samples the newest python process running a scripts/*.py entrypoint.
# back-compat: `statistics.sh out.csv` (no pid) still works; with multiple
# training processes (jax.distributed spawn) pass the rank-0 pid explicitly —
# the pgrep fallback samples only the newest matching process.
case "${1:-}" in
  ''|*[!0-9]*) PID=$(pgrep -nf 'python.*scripts/.*\.py'); OUT=${1:-tpu_log.csv} ;;
  *)           PID=$1; OUT=${2:-tpu_log.csv} ;;
esac
if [ -z "$PID" ] || [ ! -d "/proc/$PID" ]; then
  echo "statistics.sh: no training process found (pass a pid)" >&2
  exit 1
fi
echo "ts,host_rss_kb" > "$OUT"
while [ -d "/proc/$PID" ]; do
  RSS=$(awk '/VmRSS/{print $2}' "/proc/$PID/status" 2>/dev/null)
  [ -n "$RSS" ] || break   # exited or zombie: no VmRSS line
  echo "$(date +%s.%N),$RSS" >> "$OUT"
  sleep 0.5
done
