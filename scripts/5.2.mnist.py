#!/usr/bin/env python
"""Variant 5.2 — MNIST CNN with allreduce optimizer (horovod MNIST equivalent).

Reference: 5.2.horovod_pytorch_mnist.py — LeNet-style Net, batch 64, lr 0.01
scaled by world size, fp16 allreduce on by default, Adasum option, gradient
predivide factor (reference 5.2.horovod_pytorch_mnist.py:12-33,159-185).

TPU-native deltas: Adasum's scaled-sum is mapped to plain mean (documented —
Adasum's convergence trick targets hierarchical GPU rings; on a flat ICI mesh
mean is the appropriate op). Per-rank dataset dirs (reference :135) are
unnecessary: every process shards one dataset by jax.process_index().
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tpu_dist.configs import TrainConfig, parse_config
from tpu_dist.engine import Trainer
from tpu_dist.parallel import launch

DEFAULTS = TrainConfig(arch="lenet", epochs=10, batch_size=64, lr=0.01,
                       momentum=0.5, weight_decay=0.0, dataset="mnist",
                       variant="shard_map", grad_compression="bf16",
                       lr_scale_by_world=True)

if __name__ == "__main__":
    cfg = parse_config(defaults=DEFAULTS, description=__doc__)
    info = launch.initialize()
    best = Trainer(cfg).fit()
    print(f"best_acc1 {best * 100:.3f}")
