#!/bin/bash
# variant 5.2 fp16: bf16-compressed gradient allreduce (reference 5.2.run.mnist.fp16.sh:3)
python scripts/5.2.mnist.py --grad-compression bf16 "$@"
