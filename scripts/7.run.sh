#!/bin/bash
# variant 7: the TPU-native flagship (BASELINE.json north star)
python scripts/7.jax_tpu.py "$@"
