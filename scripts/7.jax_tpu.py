#!/usr/bin/env python
"""Variant 7 — the flagship TPU-native path (BASELINE.json north star).

The "sixth backend" the reference never had: ResNet-50 / CIFAR-10 on a TPU
pod. jit+mesh data parallelism, bf16 compute with fp32 master weights and BN
stats, on-device normalize fused into the step, double-buffered host->HBM
prefetch, exact psum'd distributed eval, process-0 checkpointing with real
resume. Single chip to multi-host pod with the same script: processes join
via tpu_dist.parallel.launch (TPU metadata / TPU_DIST_* / Slurm env).
"""

import argparse
import dataclasses
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tpu_dist.configs import TrainConfig, add_args
from tpu_dist.engine import Trainer
from tpu_dist.parallel import launch

DEFAULTS = TrainConfig(arch="resnet50", epochs=10, batch_size=1024,
                       dataset="cifar10", variant="jit", precision="bf16",
                       steps_per_dispatch=16, log_csv="jax_tpu.csv")

if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__)
    add_args(parser, DEFAULTS)
    # sentinel default: 'not passed' is distinguishable from an explicit 16,
    # so the jit-only 16-step default downgrades for shard_map but any
    # EXPLICIT value (prefix abbreviations included — argparse resolves
    # them) reaches Trainer's validation and errors clearly
    parser.set_defaults(steps_per_dispatch=None)
    ns = parser.parse_args()
    if ns.steps_per_dispatch is None:
        ns.steps_per_dispatch = (DEFAULTS.steps_per_dispatch
                                 if ns.variant == "jit" else 1)
    cfg = TrainConfig(**{f.name: getattr(ns, f.name)
                         for f in dataclasses.fields(TrainConfig)})
    info = launch.initialize()
    print(f"[proc {info.process_id}/{info.num_processes}] via {info.method}")
    if cfg.max_restarts > 0:
        # in-process self-healing (parallel.supervisor): HealthError halts
        # and organic crashes rebuild the trainer with attempt lineage and
        # resume from the newest valid checkpoint. Process-killing faults
        # need the subprocess flavor: python -m tpu_dist.supervise -- ...
        from tpu_dist.parallel.supervisor import run_supervised
        best = run_supervised(Trainer, cfg)
    else:
        best = Trainer(cfg).fit()
    print(f"best_acc1 {best * 100:.3f}")
