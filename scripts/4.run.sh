#!/bin/bash
# variant 4: bf16 mixed precision (reference 4.run.sh:3 apex AMP)
python scripts/4.bf16_distributed.py "$@"
