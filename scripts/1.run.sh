#!/bin/bash
# variant 1: single process, all local TPU chips (reference 1.run.sh:3)
python scripts/1.dataparallel.py "$@"
