#!/bin/bash
# Static-analysis gate: distlint over the acceptance surface, plus the
# ledger-schema rule over tests/scripts. Stdlib-only (no jax, no devices),
# so this runs anywhere — pre-commit, CI, a laptop. Non-zero exit on any
# unsuppressed finding; suppressions require written reasons by design.
#
# DL006 (the absorbed tools/check_ledger_schema) covers every emit site in
# the union of these two invocations — including the round-9 ones: the
# health sentry (tpu_dist/obs/health.py), the metrics snapshot
# (tpu_dist/obs/__init__.py), and the trace-merge/report readers in tools/.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m tools.distlint tpu_dist tools bench.py "$@"
python -m tools.distlint --select DL006 tests scripts

# Bench-trajectory gate (tools/bench_track.py, stdlib-only): the newest
# checked-in BENCH_r*.json must not have dropped >5% below the metric's
# trailing best — the apex-data_prefetcher class of silent regression.
python tools/bench_track.py --check
