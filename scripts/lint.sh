#!/bin/bash
# Static-analysis gate: distlint over the acceptance surface, plus the
# ledger-schema rule over tests/scripts. Stdlib-only (no jax, no devices),
# so this runs anywhere — pre-commit, CI, a laptop. Non-zero exit on any
# unsuppressed finding; suppressions require written reasons by design.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m tools.distlint tpu_dist tools bench.py "$@"
python -m tools.distlint --select DL006 tests scripts
