#!/bin/bash
# Static-analysis gate: distlint over the FULL acceptance surface —
# tpu_dist, tools (the linter lints itself), tests, scripts, bench.py.
# Stdlib-only (no jax, no devices), so this runs anywhere — pre-commit,
# CI, a laptop. The run also writes distlint.sarif (SARIF 2.1.0) as a CI
# code-scanning artifact. Exit code gates on ERROR-tier findings only:
# warn-tier rules (DL102/DL103) report without failing the build, and
# suppressions require written reasons by design (--debt below keeps the
# inventory honest).
set -euo pipefail
cd "$(dirname "$0")/.."

# One run does all three jobs: the error-tier gate, the SARIF artifact,
# and the advisory suppression-debt inventory (--with-debt reuses the
# same lint result — no second full sweep of the call graph).
python -m tools.distlint --sarif-out distlint.sarif --with-debt "$@"

# Bench-trajectory gate (tools/bench_track.py, stdlib-only): the newest
# checked-in BENCH_r*.json must not have dropped >5% below the metric's
# trailing best — the apex-data_prefetcher class of silent regression.
python tools/bench_track.py --check

# Supervisor-policy gate (round 10) + consensus-policy gate (round 13),
# jax-free BY CONSTRUCTION: the elastic supervisor AND its cross-host
# consensus must keep working on a bare login/CI host (no jax installed),
# so this pass hard-blocks jax imports and runs the restart classification,
# backoff math, degraded-shrink, fault-spec grammar, dense renumbering and
# shrink->re-expand membership cycle as units. A stray `import jax`
# creeping into parallel.supervisor / parallel.consensus / obs.faults /
# the lazy parallel __init__ fails HERE, before any pod notices.
python - <<'EOF'
import builtins, signal, tempfile

_real = builtins.__import__
def _guard(name, *a, **k):
    if name == "jax" or name.startswith("jax."):
        raise ImportError(f"supervisor policy gate: jax import blocked ({name})")
    return _real(name, *a, **k)
builtins.__import__ = _guard

from tpu_dist.obs.faults import FaultPlan
from tpu_dist.parallel.supervisor import (PREEMPT_SNAPSHOT_RC, RestartPolicy,
                                          classify_attempt, compute_backoff,
                                          degraded_env)
from tpu_dist.supervise import build_parser

pol = RestartPolicy(backoff_base_s=1.0, backoff_max_s=8.0)
assert [compute_backoff(n, pol) for n in (0, 1, 2, 3, 9)] == \
    [0.0, 1.0, 2.0, 4.0, 8.0]
# per-host jitter: deterministic, decorrelated, bounded
waits = [compute_backoff(3, pol, host_id=h) for h in range(4)]
assert len(set(waits)) == 4
assert all(4.0 <= w <= 4.0 * (1 + pol.backoff_jitter) for w in waits)
assert waits == [compute_backoff(3, pol, host_id=h) for h in range(4)]
end = {"event": "run_end", "status": "crashed",
       "error": "HealthError: val_loss spike"}
assert classify_attempt([end], 1) == "health_halt"
assert classify_attempt([], -signal.SIGTERM) == "preemption"
assert classify_attempt([], PREEMPT_SNAPSHOT_RC) == "preemption_snapshotted"
assert classify_attempt(
    [{"event": "run_end", "status": "preempted"}], None) == \
    "preemption_snapshotted"
assert classify_attempt([], 1, stderr_tail="rendezvous failed") == "rendezvous"
assert classify_attempt([{"event": "stall"}], -9, True) == "stall"
assert classify_attempt([], 13) == "crash"
env, n = degraded_env({"TPU_DIST_NUM_PROCESSES": "4"})
assert n == 3 and env["TPU_DIST_DEGRADED"] == "1"
plan = FaultPlan.parse("hard_exit@step=10,attempt=0;rendezvous_fail@times=2;"
                       "preempt_deadline@step=5;host_return@nth=2")
assert plan.sites() == {"hard_exit", "rendezvous_fail", "preempt_deadline",
                        "host_return"}
build_parser().parse_args(["--ledger", "x.jsonl", "--", "true"])

# consensus-policy gate: one full shrink -> renumber -> re-expand cycle on
# real files, no jax anywhere on the import path
from tpu_dist.parallel.consensus import ConsensusDir, consensus_env

with tempfile.TemporaryDirectory() as d:
    now = [1000.0]
    hosts = [ConsensusDir(d, h, planned=3, lease_s=5.0,
                          now=lambda: now[0]) for h in range(3)]
    for c in hosts:
        c.register()
    view = hosts[0].resolve()
    assert view.epoch == 0 and view.hosts == (0, 1, 2)
    hosts[1].leave()                       # mid-numbered host loss
    view = hosts[2].resolve()
    assert view.epoch == 1 and view.hosts == (0, 2) and view.degraded
    assert view.process_id(2) == 1         # the id hole is CLOSED
    cenv = consensus_env({}, view, 2)
    assert cenv["TPU_DIST_PROCESS_ID"] == "1"
    assert cenv["TPU_DIST_DEGRADED"] == "1"
    hosts[1].register()                    # the lost host returns
    view = hosts[0].resolve()
    assert view.epoch == 2 and view.hosts == (0, 2, 1)  # survivors first
    assert not view.degraded and view.process_id(1) == 2

# fleet-scenario gate (round 14): the schedule grammar + deterministic
# compiler and the fleet stitcher must import and run jax-free — the CI
# scenario is validated and its compile double-checked for determinism
from tpu_dist.sim.scenario import (compile_host_plans, expected_restart_classes,
                                   load_scenario)
from tpu_dist.sim.fleet import FleetLedger

sc = load_scenario("scripts/fleet_ci.json")
p1, a1 = compile_host_plans(sc)
p2, a2 = compile_host_plans(sc)
assert ([ (x.tick, x.rid, x.tenant, x.prompt_len, x.out_len)
          for h in sorted(p1) for x in p1[h].arrivals ] ==
        [ (x.tick, x.rid, x.tenant, x.prompt_len, x.out_len)
          for h in sorted(p2) for x in p2[h].arrivals ]) and a1 == a2
assert {h: p.faults for h, p in p1.items() if p.faults}  # >= 1 fault wave
classes = expected_restart_classes(sc)
assert all(cls[-1] == "clean" for cls in classes.values())
assert FleetLedger({0: []}).hosts == {0: []}
print("supervisor + consensus + fleet-scenario policy gates: OK (no jax)")
EOF

# Plan-IR + auto-tuner gate (round 15), jax-free BY CONSTRUCTION: the
# step-plan IR and the tuner must import and run on a bare login/CI host
# (tools/tune.py's whole point), and the tuner's output must be
# DETERMINISTIC — the config knob, bench tags and ledger stamps all key
# on the plan hash, so two identical searches must emit byte-identical
# plan JSON. A stray `import jax` creeping into plan.ir / plan.tune
# fails HERE.
python - <<'EOF'
import builtins, json

_real = builtins.__import__
def _guard(name, *a, **k):
    if name == "jax" or name.startswith("jax."):
        raise ImportError(f"plan gate: jax import blocked ({name})")
    return _real(name, *a, **k)
builtins.__import__ = _guard

from tpu_dist.plan.ir import (Plan, PlanError, apply_plan_to_config,
                              load_plan_file, plan_for_device, plan_hash)
from tpu_dist.plan.tune import tune

# IR round-trip + hash determinism + validation
p = Plan(engine="lm", quant="int8", grad_bucket_mb=25.0, sync="explicit",
         window="indexed", steps_per_dispatch=16,
         quant_block=(256, 128, 0)).validate()
assert Plan.from_json(p.to_json()) == p
assert plan_hash(p) == plan_hash(Plan.from_json(p.to_json()))
for bad in (dict(quant="int4"), dict(tp_impl="ring"),
            dict(grad_bucket_mb=25.0), dict(quant_block=(100, 128, 0))):
    try:
        Plan(engine="lm", **bad).validate()
    except PlanError:
        pass
    else:
        raise AssertionError(f"accepted invalid plan {bad}")

# the canned-measurement search, twice: byte-identical plan JSON
text1, res1 = tune(measurement_files=["scripts/tune_ci.json"])
text2, res2 = tune(measurement_files=["scripts/tune_ci.json"])
assert text1 == text2, "tuner output is not deterministic"
best = res1["TPU v5 lite"]["best"]
assert best["measured"], "the canned trial must win (measured refinement)"
doc = json.loads(text1)
assert doc["plans"]["TPU v5 lite"]["hash"] == best["hash"]
# the emitted file round-trips through the config knob's loader
import os, tempfile
fd, tmp = tempfile.mkstemp(suffix=".json"); os.close(fd)
try:
    with open(tmp, "w") as f:
        f.write(text1)
    sel = plan_for_device(load_plan_file(tmp), "TPU v5 lite")
    assert plan_hash(sel) == best["hash"]
finally:
    os.unlink(tmp)
print("plan IR + tuner gate: OK (no jax, deterministic)")
EOF

# Request-observatory gate (round 17), jax-free BY CONSTRUCTION: the
# span model (obs.reqtrace) and the reading side (tools/request_report)
# must run on a bare login/CI host, and the report must be DETERMINISTIC
# — same ledger bytes, same report bytes. Built twice from the canned
# two-host fixture (rid 5 shed on host 0, re-admitted on host 1) with
# fresh loads, then the invariants the fixture encodes are asserted: one
# cross-host trace, coverage 1.0 with the sum-check green, and every slo
# breach holding >= 1 exemplar. A stray `import jax` creeping into
# obs.reqtrace / sim.fleet / the report tool fails HERE.
python - <<'EOF'
import builtins, json

_real = builtins.__import__
def _guard(name, *a, **k):
    if name == "jax" or name.startswith("jax."):
        raise ImportError(f"reqtrace gate: jax import blocked ({name})")
    return _real(name, *a, **k)
builtins.__import__ = _guard

from tools.request_report import render, requests_summary
from tpu_dist.sim.fleet import FleetLedger

FIX = "tests/fixtures/reqtrace"

def build():
    records = FleetLedger.discover(FIX).merged()
    summary = requests_summary(records)
    lines = []
    render(summary, records, out=lines.append, waterfalls=5)
    return summary, json.dumps(summary, default=str) + "\n".join(lines)

summary, text1 = build()
_, text2 = build()
assert text1 == text2, "request report is not deterministic"
assert summary["cross_host_traces"] == 1, summary
ta = summary["tail_attribution"]
assert ta["coverage"] == 1.0 and ta["sum_check"]["ok"], ta
assert summary["slo_exemplars"], "fixture breach lost"
assert all(b["exemplars"] for b in summary["slo_exemplars"]), \
    "a breach resolved to no exemplar"
print("reqtrace gate: OK (no jax, deterministic)")
EOF

# Autoscaling gate (round 20), jax-free BY CONSTRUCTION: the capacity
# monitor closes the observability->capacity loop, so its policy grammar,
# the checked-in acceptance scenario, and the decision replay must all
# run on a bare login/CI host — and the replay must be DETERMINISTIC
# (decision ids, attribution, ordering), because the fleet report and
# the supervisor's applied follow-ups all key on the decision id. The
# canned fixture is built twice from fresh loads and must produce
# byte-identical decisions, pinned to the [up, down] pair it encodes.
python - <<'EOF'
import builtins, json

_real = builtins.__import__
def _guard(name, *a, **k):
    if name == "jax" or name.startswith("jax."):
        raise ImportError(f"autoscale gate: jax import blocked ({name})")
    return _real(name, *a, **k)
builtins.__import__ = _guard

from tpu_dist.obs.autoscale import AutoscalePolicy, replay_decisions
from tpu_dist.sim.scenario import compile_host_plans, load_scenario

pol = AutoscalePolicy.load("scripts/autoscale_policy.json")
assert pol.min_hosts == 2 and pol.max_hosts == 3, pol
assert pol.down.stable_ticks >= 1, "down-side hysteresis lost"

# the acceptance scenario parses and compiles deterministically with its
# autoscale block (standby host parked, policy by repo-relative path)
sc = load_scenario("scripts/fleet_autoscale.json")
assert sc.standby_hosts() == [2], sc.autoscale
p1, a1 = compile_host_plans(sc)
p2, a2 = compile_host_plans(sc)
assert ([(x.tick, x.rid, x.tenant, x.prompt_len, x.out_len)
         for h in sorted(p1) for x in p1[h].arrivals] ==
        [(x.tick, x.rid, x.tenant, x.prompt_len, x.out_len)
         for h in sorted(p2) for x in p2[h].arrivals]) and a1 == a2

def replay():
    with open("tests/fixtures/autoscale/ledger.jsonl") as f:
        recs = [json.loads(line) for line in f]
    return replay_decisions(
        recs, AutoscalePolicy.load("scripts/autoscale_policy.json"),
        hosts0=2)

d1, d2 = replay(), replay()
assert json.dumps(d1) == json.dumps(d2), \
    "decision replay is not deterministic"
assert [(d["decision"], d["direction"], d["signal"]) for d in d1] == \
    [("d0", "up", "slo_breaches_window"), ("d1", "down", "calm_ticks")], d1
assert d1[0]["tick"] == 14 and d1[1]["tick"] == 64, d1
print("autoscale gate: OK (no jax, deterministic)")
EOF

# Program-audit gate (round 18): proglint over every plan in the tuner's
# canned-CI candidate space (scripts/tune_ci.json names the device kind).
# Unlike the gates above this one NEEDS jax — it traces real programs —
# so it is guarded on availability instead of blocking the import: a
# bare login host still runs every other gate. Abstract tracing only
# (eval_shape-class work, CPU, nothing executes); run TWICE because the
# canonical report is a CI artifact and artifact diffing needs it
# byte-deterministic. Publishes proglint.json + proglint.sarif next to
# distlint.sarif.
if python -c "import jax" >/dev/null 2>&1; then
python - <<'EOF'
import json

import jax

jax.config.update("jax_platforms", "cpu")
from tpu_dist._compat import set_cpu_device_count

set_cpu_device_count(8)
from tpu_dist.analysis.proglint import Finding, audit_tune_space, to_sarif

with open("scripts/tune_ci.json") as f:
    json.load(f)   # the canned space must exist and parse

r1 = audit_tune_space()
r2 = audit_tune_space()
text = json.dumps(r1, indent=1, sort_keys=True) + "\n"
assert text == json.dumps(r2, indent=1, sort_keys=True) + "\n", \
    "proglint report is not byte-deterministic"
assert r1["unwaivered"] == 0, \
    "unwaivered program-audit findings:\n" + "\n".join(
        Finding(**d).render() for d in r1["findings"] if not d["waived"])
with open("proglint.json", "w") as f:
    f.write(text)
with open("proglint.sarif", "w") as f:
    json.dump(to_sarif([Finding(**d) for d in r1["findings"]]), f,
              indent=2, sort_keys=True)
    f.write("\n")
print(f"proglint gate: OK ({r1['plans']} plan(s) -> {r1['programs']} "
      f"program(s), {r1['unwaivered']} unwaivered, deterministic)")
EOF
else
    echo "proglint gate: SKIPPED (no jax on this host; program tracing needs it)"
fi

# Advisory tier-1 budget creep warning (never fails the gate): conftest
# writes each full-suite run's wall time + top-20 durations to
# /tmp/tier1_durations.json (TPU_DIST_TIER1_DURATIONS overrides); the
# suite dies at the 870s timeout, so a wall beyond 700s deserves eyes on
# the top offenders BEFORE the timeout rediscovers it the hard way.
python - <<'EOF' || true
import json, os
path = os.environ.get("TPU_DIST_TIER1_DURATIONS", "/tmp/tier1_durations.json")
try:
    with open(path) as f:
        d = json.load(f)
except Exception:
    raise SystemExit(0)  # no recorded run on this machine — nothing to say
wall = d.get("wall_s") or 0
if wall > 700:
    print(f"WARNING: last tier-1 run took {wall:.0f}s of the 870s budget "
          f"({d.get('tests', '?')} tests; advisory only). Top offenders:")
    for t in (d.get("top") or [])[:8]:
        print(f"  {t.get('s', 0):7.1f}s  {t.get('nodeid', '?')}")
    print("  -> slow-mark new heavy tests (pyproject 'slow' marker) or "
          "shrink the biggest ones above.")
EOF
