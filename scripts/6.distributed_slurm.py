#!/usr/bin/env python
"""Variant 6 — Slurm multi-node training (ImageNet).

Reference: 6.distributed_slurm_main.py — rank from SLURM_PROCID, world from
SLURM_NPROCS, file:// rendezvous keyed by SLURM_JOBID, per-node mp.spawn,
ImageFolder/ImageNet, 90 epochs (reference 6.distributed_slurm_main.py:89-101,
130-159; start.sh:5). Marked "Not Tested Yet" upstream (README_EN.md:17).

TPU-native: `srun -N<nodes> python scripts/6.distributed_slurm.py` — one
process per host; tpu_dist.parallel.launch reads SLURM_* and rendezvouses over
DCN (no shared-FS file:// needed, no per-node spawn: each process drives all
its chips). Fixes two reference bugs: checkpointing is process-0-guarded
(reference wrote from every node, 6...py:190) and eval is sharded (reference
val loader was not distributed, 6...py:148-159).
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from tpu_dist.configs import TrainConfig, parse_config
from tpu_dist.engine import Trainer
from tpu_dist.parallel import launch

DEFAULTS = TrainConfig(arch="resnet50", epochs=90, batch_size=3200,
                       dataset="imagenet", variant="jit",
                       log_csv="distributed.csv")

if __name__ == "__main__":
    cfg = parse_config(defaults=DEFAULTS, description=__doc__)
    info = launch.initialize()
    print(f"[proc {info.process_id}/{info.num_processes}] via {info.method}")
    best = Trainer(cfg).fit()
    print(f"best_acc1 {best * 100:.3f}")
