#!/bin/bash
# variant 3: in-process spawn (reference 3.run.sh:3). TPU: nprocs=1 is canonical;
# TPU_DIST_NPROCS_SPAWN=4 forks a loopback-TCP CPU simulation of 4 hosts.
python scripts/3.multiprocessing_spawn.py "$@"
