#!/bin/bash
# cheat-sheet of all launch commands (reference start.sh:1-5)
bash scripts/1.run.sh
bash scripts/2.run.sh
bash scripts/3.run.sh
bash scripts/4.run.sh
bash scripts/5.run.sh
bash scripts/5.2.run.mnist.sh
# srun -N2 bash scripts/6.run.sh
bash scripts/7.run.sh
