#!/bin/bash
# variant 5: explicit-allreduce engine (reference 5.run.sh:3 horovodrun -np 4)
python scripts/5.allreduce_distributed.py "$@"
